"""Continuous-batching scheduler: admission queue, slots, preemption.

Pure host logic — no jax imports — so the batching policy is unit-
testable without compiling anything. The
:class:`~tensorframes_tpu.serve.engine.GenerationEngine` drives it:

- :meth:`Scheduler.submit` parks requests in a BOUNDED admission queue
  (a full queue rejects or blocks — backpressure instead of unbounded
  host memory, the same stance the scoring server takes with its
  connection semaphore).
- :meth:`Scheduler.admit` moves queued requests into free decode slots,
  reserving prompt pages; with a :class:`~.kv_pages.PrefixCache`
  attached, the longest cached page-aligned prefix of the prompt is
  refcount-shared into the new sequence first, and only the uncached
  remainder is allocated fresh.
- :meth:`Scheduler.grow` reserves the next decode position's page for a
  running sequence; on :class:`PagePoolExhausted` it first EVICTS
  prefix-cache entries (cold cached prefixes go before live work), then
  PREEMPTS the youngest other sequence — pages freed, request requeued
  at the FRONT of the queue with its progress folded into the prompt
  (recompute-style preemption: the re-admitted prefill replays prompt +
  emitted tokens, so the consumer's stream continues without replay or
  loss).

Preemption rides the failure taxonomy in ``utils/failures.py``
(:func:`record_preemption`, :class:`PagePoolExhausted`) — pool
exhaustion is a RESOURCE_EXHAUSTED condition the scheduler degrades
through, never a crash.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

import numpy as np

from ..utils.failures import (
    DeadlineExceededError,
    PagePoolExhausted,
    record_preemption,
)
from . import tenancy as _tenancy
from .kv_pages import PagePool, SequencePages, pages_needed

__all__ = [
    "GenerationHandle",
    "GenRequest",
    "QueueFullError",
    "Scheduler",
]


class QueueFullError(RuntimeError):
    """The bounded admission queue is at capacity (non-blocking submit)."""


class GenerationHandle:
    """The caller's end of one request: a token stream plus completion
    state. Iterating yields generated token ids as the engine emits them;
    :meth:`result` blocks for the full generation."""

    _DONE = object()

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._q: "queue.Queue" = queue.Queue()
        self._tokens: List[int] = []
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        #: per-request timing breakdown, filled by the engine as the
        #: stream progresses: ``queue_wait_s`` (submit -> first admit),
        #: ``prefill_s`` (sum of prefill dispatch walls — replays and
        #: recompute-style preemptions accumulate), ``prefill_chunks``
        #: (chunked-prefill dispatches), ``decode_s`` (sum of
        #: inter-emission gaps), ``replays`` (fleet failovers) — plus
        #: the cost-attribution keys the engine's finish hook records
        #: (``tokens``, ``kv_pages``, ``prefix_cached_tokens``,
        #: ``est_flops``, ``tenant``; ``obs/requests.py``). The
        #: serving endpoint echoes this dict in the HTTP response
        #: (docs/observability.md).
        self.timings: dict = {}

    # -- engine side -------------------------------------------------------

    def _emit(self, token: int) -> None:
        self._tokens.append(int(token))
        self._q.put(int(token))

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self._error = error
        self._done.set()
        self._q.put(self._DONE)

    # -- caller side -------------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Generated tokens (prompt excluded), blocking until done."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not done within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return np.asarray(self._tokens, np.int32)


@dataclass
class GenRequest:
    """One admission-queue entry. ``prompt`` already includes any tokens
    generated before a preemption (recompute-style requeue), and
    ``emitted`` counts them so re-admission emits only NEW tokens."""

    request_id: int
    prompt: np.ndarray  # [plen] int32
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0
    eos_id: Optional[int] = None
    handle: GenerationHandle = None  # type: ignore[assignment]
    submitted_at: float = field(default_factory=time.monotonic)
    emitted: int = 0  # tokens already streamed (pre-preemption progress)
    #: absolute ``time.monotonic()`` deadline, or None for no deadline;
    #: the engine's step sweep evicts expired requests (queued OR
    #: mid-generation) with :class:`DeadlineExceededError`
    deadline_t: Optional[float] = None
    #: the request's :class:`~tensorframes_tpu.obs.TraceContext` — the
    #: engine's per-request spans (prefill, prefill chunks) join this
    #: trace on the stepping thread, so one trace_id follows the request
    #: from the HTTP ingress through placement, prefill, and any
    #: failover replay (docs/observability.md)
    trace: Optional[object] = None
    #: cost-attribution key (``obs/requests.py``): who this request is
    #: billed to. The serving layer defaults it to the fleet session id
    #: when the client names no tenant; empty means unattributed.
    tenant: str = ""
    #: scheduling rank from the tenant's QoS policy at submission
    #: (``serve/tenancy.py`` ``PRIORITIES``: 0 batch, 1 standard,
    #: 2 interactive). With the QoS plane off every request carries the
    #: default 1 and ordering degenerates to pure FIFO.
    priority: int = 1


class _Active:
    """A slot's running sequence: request + page holdings + progress."""

    __slots__ = (
        "req", "seq", "generated", "admit_order", "last_emit_t",
        "prefill_pos", "cached_tokens", "cow_src", "draft_pos", "spec_k",
    )

    def __init__(self, req: GenRequest, seq: SequencePages, admit_order: int):
        self.req = req
        self.seq = seq
        self.generated: List[int] = []
        self.admit_order = admit_order
        self.last_emit_t: Optional[float] = None
        #: prompt positions whose k/v are already in this sequence's
        #: pages (a prefix-cache hit starts this > 0; chunked prefill
        #: advances it one chunk per engine step until it reaches the
        #: prompt length). The slot joins the decode batch only once the
        #: first token is emitted (``generated`` non-empty).
        self.prefill_pos = 0
        #: prompt positions covered by the prefix cache at admission
        self.cached_tokens = 0
        #: donor page to copy-on-write before prefilling (a cached
        #: prefix that ends inside this page); carries one temporary
        #: pool reference the holder must drop — the engine drops it
        #: after cloning, finish/preempt drop it when the slot dies
        #: first
        self.cow_src: Optional[int] = None
        #: SPECULATIVE-length bookkeeping (the engine's draft model,
        #: docs/serving_llm.md "Speculative decoding"): positions whose
        #: DRAFT-model KV is valid. Host state only — a preemption or
        #: restart re-admits through a fresh ``_Active``, so rejected or
        #: stale speculative draft KV "rolls back" by this counter (and
        #: the page tables) resetting, never by undoing page writes. A
        #: prefix-cache hit seeds it at ``cached_tokens`` (the shared
        #: pages carry the donor's draft KV rows too).
        self.draft_pos = 0
        #: the per-slot ADAPTIVE draft length: -1 until the engine's
        #: first speculative step seeds it from the compiled static k;
        #: the controller shrinks it on cold (low-acceptance) slots and
        #: grows it back on hot ones, bounded by the static k. Dies with
        #: the slot like ``draft_pos``.
        self.spec_k = -1

    @property
    def length(self) -> int:
        """Positions written to the KV pages so far."""
        return len(self.req.prompt) + len(self.generated)

    @property
    def remaining(self) -> int:
        return self.req.max_new_tokens - len(self.generated)


class Scheduler:
    """Slot + queue + page bookkeeping for one decode batch. Thread-safe
    for concurrent :meth:`submit`; the step-side methods (:meth:`admit`,
    :meth:`grow`, :meth:`finish`) are called by the engine's single
    stepping thread."""

    def __init__(
        self,
        pool: PagePool,
        max_slots: int,
        queue_capacity: int,
        max_seq_len: int,
        prefix_cache=None,
    ):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1; got {max_slots}")
        self.pool = pool
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len)
        self.queue_capacity = int(queue_capacity)
        #: optional :class:`~.kv_pages.PrefixCache`: admission shares
        #: cached prompt-prefix pages into new sequences, and pool
        #: exhaustion evicts cache entries before preempting live work
        self.prefix_cache = prefix_cache
        self.slots: List[Optional[_Active]] = [None] * self.max_slots
        self._waiting: Deque[GenRequest] = deque()
        self._lock = threading.Condition()
        self._admit_counter = 0
        #: optional ``fn(act, error)`` called by :meth:`finish` while
        #: the slot still holds its pages — the engine hangs its
        #: per-request cost attribution here (page count, token totals)
        #: without the scheduler importing any observability
        self.on_request_done = None
        #: optional ``fn(victim_idx) -> bool`` consulted by :meth:`grow`
        #: BEFORE preempting a pool-pressure victim: return True after
        #: having freed the victim's pages some other way (the fleet
        #: installs live KV-page migration here, ``serve/tiers.py`` —
        #: the victim's stream continues on another replica instead of
        #: paying a recompute-style preemption). False, an exception,
        #: or no hook falls through to :meth:`preempt` — preemption is
        #: always the fallback, never removed.
        self.on_pressure = None

    # -- admission ---------------------------------------------------------

    def submit(
        self,
        req: GenRequest,
        block: bool = True,
        timeout: Optional[float] = None,
    ) -> None:
        """Park ``req`` in the admission queue. A full queue blocks (the
        default — backpressure to the producer) or raises
        :class:`QueueFullError` with ``block=False``."""
        total = len(req.prompt) + req.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(req.prompt)}) + max_new_tokens "
                f"({req.max_new_tokens}) = {total} exceeds max_seq_len "
                f"{self.max_seq_len}"
            )
        if pages_needed(total, self.pool.page_size) > self.pool.num_pages:
            raise ValueError(
                f"request needs {pages_needed(total, self.pool.page_size)} "
                f"pages at full length but the pool holds only "
                f"{self.pool.num_pages} — it could never be scheduled"
            )
        with self._lock:
            deadline = None if timeout is None else time.monotonic() + timeout
            while len(self._waiting) >= self.queue_capacity:
                if not block:
                    raise QueueFullError(
                        f"admission queue full "
                        f"({self.queue_capacity} requests waiting)"
                    )
                rem = None if deadline is None else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise QueueFullError(
                        f"admission queue still full after {timeout}s"
                    )
                self._lock.wait(rem)
            self._waiting.append(req)
            self._lock.notify_all()

    def _requeue_front(self, req: GenRequest) -> None:
        """Preempted requests skip the line — they already waited once and
        hold the earliest arrival times. The queue bound is deliberately
        ignored here: a preemption must never deadlock against a full
        queue (the pages are already released; the request has nowhere
        else to live)."""
        with self._lock:
            self._waiting.appendleft(req)
            self._lock.notify_all()

    # -- stepping side -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._waiting)

    @property
    def active(self) -> List[Tuple[int, _Active]]:
        """(slot index, active sequence) pairs, oldest admission first —
        the decode order, and the inverse of the preemption order."""
        pairs = [
            (i, a) for i, a in enumerate(self.slots) if a is not None
        ]
        pairs.sort(key=lambda p: p[1].admit_order)
        return pairs

    def has_work(self) -> bool:
        return any(s is not None for s in self.slots) or self.queue_depth > 0

    def admit(self) -> List[Tuple[int, _Active]]:
        """Fill free slots from the queue head, reserving each admitted
        prompt's pages. Stops at the first request whose prompt pages the
        pool cannot supply right now (it keeps its queue position; active
        sequences finishing will free pages — preemption is only for
        sequences already mid-flight, see :meth:`grow`). Returns the new
        (slot, active) pairs for the engine to prefill."""
        admitted: List[Tuple[int, _Active]] = []
        for idx in range(self.max_slots):
            if self.slots[idx] is not None:
                continue
            with self._lock:
                if not self._waiting:
                    break
                if _tenancy.enabled():
                    # (priority, arrival): highest class first, and
                    # WITHIN a class the frontmost queue position —
                    # deque order is the arrival proxy, so preempted
                    # requests (requeued at the front) keep their
                    # earned seniority
                    best = max(
                        range(len(self._waiting)),
                        key=lambda j: (self._waiting[j].priority, -j),
                    )
                    req = self._waiting[best]
                    del self._waiting[best]
                else:
                    req = self._waiting.popleft()
                self._lock.notify_all()
            seq = SequencePages(self.pool)
            cow_src: Optional[int] = None
            cached = 0
            if self.prefix_cache is not None:
                shared, cow_src, cached = self.prefix_cache.acquire(
                    req.prompt
                )
                seq.pages = shared  # refcounted by acquire; release() frees
            try:
                try:
                    seq.ensure(len(req.prompt))
                except PagePoolExhausted:
                    if self.prefix_cache is None:
                        raise
                    # cold cached prefixes go before live admissions —
                    # but only the SHORTFALL beyond the pool's free
                    # pages, so warm prefixes the pool could keep are
                    # not over-evicted; the retried ensure re-raises if
                    # eviction could not cover it
                    missing = pages_needed(
                        len(req.prompt), self.pool.page_size
                    ) - len(seq.pages)
                    shortfall = missing - self.pool.pages_free
                    if shortfall > 0:
                        self.prefix_cache.evict_pages(shortfall)
                    seq.ensure(len(req.prompt))
            except PagePoolExhausted:
                if cow_src is not None:
                    self.pool.free([cow_src])
                seq.release()
                self._requeue_front(req)
                break
            act = _Active(req, seq, self._admit_counter)
            act.cached_tokens = cached
            act.cow_src = cow_src
            self._admit_counter += 1
            self.slots[idx] = act
            admitted.append((idx, act))
        return admitted

    def grow(self, idx: int) -> bool:
        """Reserve the page holding slot ``idx``'s next decode position,
        preempting the YOUNGEST other active sequence per retry until the
        pool yields one. Returns False when ``idx``'s own sequence got
        preempted (it was the youngest left — the caller drops it from
        this step's batch)."""
        act = self.slots[idx]
        assert act is not None
        while True:
            try:
                # the pending token writes at position length - 1 (its
                # ``generated`` entry exists but is not yet in the cache)
                act.seq.ensure(act.length)
                return True
            except PagePoolExhausted:
                if (
                    self.prefix_cache is not None
                    and self.prefix_cache.evict_pages(1) > 0
                ):
                    continue  # a cold cached prefix paid instead
                victim_idx = self._victim_slot(exclude=idx)
                if victim_idx is None:
                    # nothing left to evict but the requester itself; its
                    # full-length feasibility was checked at submit, so
                    # alone it always fits — reaching here means it is
                    # NOT alone in page ownership yet no slot can be
                    # preempted, which cannot happen with slot-owned pages
                    self.preempt(idx)
                    return False
                if self.on_pressure is not None:
                    try:
                        if self.on_pressure(victim_idx):
                            # the victim's pages were freed by migration
                            # (its stream continues elsewhere) — retry
                            # the reservation before preempting anyone
                            continue
                    except Exception:
                        # a broken hook degrades to the ladder it
                        # fronts; it must never wedge the step loop
                        pass
                if self.slots[victim_idx] is None:
                    continue  # the hook consumed the victim after all
                self.preempt(victim_idx)

    def _youngest_active(self, exclude: int) -> Optional[int]:
        """Most recently admitted slot other than ``exclude`` — the
        preemption victim (least progress lost, and the inverse of
        admission order keeps the policy starvation-free: the evicted
        request re-enters at the queue FRONT)."""
        best, best_order = None, -1
        for i, a in enumerate(self.slots):
            if a is None or i == exclude:
                continue
            if a.admit_order > best_order:
                best, best_order = i, a.admit_order
        return best

    def _victim_slot(self, exclude: int) -> Optional[int]:
        """The preemption victim other than ``exclude``. QoS plane off:
        exactly :meth:`_youngest_active`. Plane on: lowest-PRIORITY
        slot first, youngest within a class — an interactive stream is
        never evicted while a batch slot can pay, and within one class
        the least progress is lost (still starvation-free: victims
        requeue at the front and re-admit ahead of their class)."""
        if not _tenancy.enabled():
            return self._youngest_active(exclude)
        best: Optional[int] = None
        best_key: Optional[Tuple[int, int]] = None
        for i, a in enumerate(self.slots):
            if a is None or i == exclude:
                continue
            key = (a.req.priority, -a.admit_order)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def tenant_counts(self) -> Tuple[dict, dict]:
        """Per-tenant footprint: ({tenant: active slots},
        {tenant: queued requests}) — the admission gate's quota input
        and the ``/statusz`` per-tenant view."""
        active: dict = {}
        with self._lock:
            for a in self.slots:
                if a is not None:
                    active[a.req.tenant] = active.get(a.req.tenant, 0) + 1
            queued: dict = {}
            for r in self._waiting:
                queued[r.tenant] = queued.get(r.tenant, 0) + 1
        return active, queued

    def preempt(self, idx: int) -> GenRequest:
        """Evict slot ``idx``: release its pages and requeue the request
        at the queue front with progress folded into the prompt (the
        handle keeps streaming; re-admission emits only new tokens)."""
        act = self.slots[idx]
        assert act is not None
        self._drop_cow(act)
        act.seq.release()
        self.slots[idx] = None
        req = act.req
        new_req = GenRequest(
            request_id=req.request_id,
            prompt=np.concatenate(
                [req.prompt, np.asarray(act.generated, np.int32)]
            ),
            max_new_tokens=req.max_new_tokens - len(act.generated),
            temperature=req.temperature,
            top_p=req.top_p,
            seed=req.seed,
            eos_id=req.eos_id,
            handle=req.handle,
            submitted_at=req.submitted_at,
            emitted=req.emitted + len(act.generated),
            deadline_t=req.deadline_t,
            trace=req.trace,
            tenant=req.tenant,
            priority=req.priority,
        )
        record_preemption("serve")
        _tenancy.count_preemption(req.priority)
        self._requeue_front(new_req)
        return new_req

    def detach(self, idx: int) -> _Active:
        """Release slot ``idx``'s pages WITHOUT closing its handle or
        requeueing its request — the live-migration release
        (``serve/tiers.py``): the caller has already serialized the
        slot's state and will re-materialize it on another replica,
        where the SAME handle keeps streaming. Unlike :meth:`finish`
        this runs no terminal accounting (the destination engine
        accounts the request when it actually finishes) and unlike
        :meth:`preempt` it records no preemption — nothing was lost.
        Returns the detached :class:`_Active` for the caller's
        bookkeeping."""
        act = self.slots[idx]
        assert act is not None
        self._drop_cow(act)
        act.seq.release()
        self.slots[idx] = None
        return act

    def _drop_cow(self, act: _Active) -> None:
        """Release a pending copy-on-write donor reference (taken by
        ``PrefixCache.acquire``) when the slot dies before the engine
        cloned the page. Idempotent — the engine clears ``cow_src``
        itself after cloning."""
        if act.cow_src is not None:
            self.pool.free([act.cow_src])
            act.cow_src = None

    def finish(self, idx: int, error: Optional[BaseException] = None) -> None:
        """Terminal slot release: pages back to the pool, handle closed.
        ``on_request_done`` observes the slot first (pages still held,
        so holdings are countable); its failures are swallowed — an
        accounting bug must not leak pages or hang a handle."""
        act = self.slots[idx]
        assert act is not None
        if self.on_request_done is not None:
            try:
                self.on_request_done(act, error)
            except Exception:
                pass
        self._drop_cow(act)
        act.seq.release()
        self.slots[idx] = None
        act.req.handle._finish(error)

    # -- supervision -------------------------------------------------------

    def expire(self, now: float) -> int:
        """Evict every request whose deadline has passed: queued requests
        are failed in place (their handle raises
        :class:`DeadlineExceededError`), active ones release their slot
        and pages too. Returns the number evicted. Called from the
        engine's step sweep, so an expired request is gone within one
        step — it never occupies a slot the live traffic needs."""
        expired: List[GenRequest] = []
        with self._lock:
            if self._waiting:
                keep: Deque[GenRequest] = deque()
                for r in self._waiting:
                    if r.deadline_t is not None and now >= r.deadline_t:
                        expired.append(r)
                    else:
                        keep.append(r)
                if expired:
                    self._waiting = keep
                    self._lock.notify_all()  # queue shrank: wake submitters
        for r in expired:
            r.handle._finish(
                DeadlineExceededError(
                    f"request {r.request_id} exceeded its deadline while "
                    f"queued for admission"
                )
            )
        n = len(expired)
        for i, a in enumerate(self.slots):
            if (
                a is not None
                and a.req.deadline_t is not None
                and now >= a.req.deadline_t
            ):
                self.finish(
                    i,
                    error=DeadlineExceededError(
                        f"request {a.req.request_id} exceeded its deadline "
                        f"mid-generation ({len(a.generated)} of "
                        f"{a.req.max_new_tokens} tokens emitted)"
                    ),
                )
                n += 1
        return n

    def fail_all(self, error: BaseException) -> int:
        """Terminal sweep: fail EVERY in-flight request — active slots
        and the whole admission queue — with ``error``, releasing their
        pages. Returns how many handles were closed. The supervisor's
        fail-fast path: a consumer must see a doomed engine's real error
        within a step, not hang to its timeout."""
        n = 0
        for i, a in enumerate(self.slots):
            if a is not None:
                self.finish(i, error=error)
                n += 1
        with self._lock:
            drained = list(self._waiting)
            self._waiting.clear()
            if drained:
                self._lock.notify_all()
        for r in drained:
            r.handle._finish(error)
        return n + len(drained)
