"""Serving fleet: replicated engines behind a health-gated router.

Everything below ``serve/`` so far protects exactly ONE
:class:`~.engine.GenerationEngine`: the supervisor retries/degrades/
restarts it, but a terminal engine failure still fails every in-flight
request, and max throughput is one chip. The fleet is the next tier —
the deployment shape TPU serving work assumes (Ragged Paged Attention,
PAPERS.md): **N identical paged-KV engines behind one placement layer**,
where a replica death becomes a retried request, not an outage.

- :class:`Fleet` owns N replicas (same model/config, independent
  :class:`~.kv_pages.PagePool`\\ s) plus the router. :meth:`Fleet.submit`
  places each request on a healthy replica by **least-loaded** order
  (most free KV pages, then shallowest admission queue) with optional
  **session affinity** (``session=`` pins a chat/tenant to one replica's
  KV locality while it stays healthy).
- **Health gating** reuses the PR-3 supervisor machinery per replica: a
  watchdog thread polls ``engine.health()``; an unhealthy or wedged
  replica is **fenced** (no new placements), drained (every attached
  handle fails now, so its survivors replay immediately), ``restart()``\\ ed
  in the background, and re-admitted only after a **probe generation**
  (one token through prefill AND decode) succeeds.
- **Request replay** is the robustness core: the router records each
  request's prompt/params and forwards tokens through a relay, so when a
  replica dies mid-stream the survivors resubmit to a healthy replica
  *recompute-style* — already-emitted tokens fold into the prompt and
  the budget shrinks, the same trick the scheduler's preemption uses.
  Client streams never replay or lose tokens, and stay **byte-identical**
  to a solo decode for greedy and seeded-sampling requests alike
  (per-step sampling keys fold at absolute positions, so the replayed
  continuation draws the same tokens the dead replica would have).

What does NOT replay: :class:`DeadlineExceededError` (the budget already
passed) and submit-time ``ValueError`` rejections (every replica is
identical, so an infeasible request is infeasible everywhere). Replays
are capped at ``max_replays`` per request so one poison request that
deterministically kills its replica cannot churn the whole fleet
forever. Static shapes mean failover adds **zero compiled programs**:
every replica keeps its own ≤ 3 step programs for the fleet's lifetime
(≤ 5 with speculative decoding's draft + verify). Speculation composes
with replay unchanged: the relay only ever carries ACCEPTED target
tokens, so a failover folds them into the prompt exactly as today —
and replicas of DIFFERENT draft length k (or none at all) stay
byte-identical, since every k emits the target's own sampled tokens.

Chaos sites (``utils/chaos.py``): ``fleet.place`` sits in the placement
path (a ``transient`` there retries invisibly); ``fleet.replica_fault``
is polled once per replica per watchdog tick and **kills the replica
whose poll fired** — append the replica name to target one
(``fleet.replica_fault.r1=fatal:every=8`` kills ``r1`` on the 8th tick).

``interop/serving.py`` accepts ``engine=Fleet`` unchanged: ``POST
/generate`` places through the router, ``GET /healthz`` aggregates
(200 while ANY replica serves; per-replica detail in the body), and
503-shedding starts only when ALL replicas are fenced. Metrics:
``fleet.replicas_healthy``, ``fleet.failovers_total``,
``fleet.replays_total``, and per-replica pages/queue gauges with a
``replica`` label (``docs/observability.md``). Sizing guidance and the
failover cookbook: ``docs/serving_llm.md`` + ``docs/fault_tolerance.md``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import (
    current_trace as _current_trace,
    event as _trace_event,
    flight as _flight,
    use_trace as _use_trace,
)
from ..obs.metrics import counter as _counter, gauge as _gauge
from ..utils import chaos as _chaos
from ..utils.config import get_config
from ..utils.failures import (
    DeadlineExceededError,
    StaleLeaseError,
    TenantThrottledError,
    first_line as _first_line,
    run_with_retries,
)
from ..utils.logging import get_logger
from . import tenancy as _tenancy
from . import tiers as _tiers
from .engine import EngineUnhealthyError, GenerationEngine
from .scheduler import GenerationHandle, QueueFullError
from .tiers import TIERS, TierMigrationError

__all__ = ["Fleet", "FleetHandle"]

logger = get_logger("serve.fleet")

_m_replicas_healthy = _gauge(
    "fleet.replicas_healthy",
    "Replicas currently accepting placements (active and healthy)",
)
_m_failovers = _counter(
    "fleet.failovers_total",
    "Replicas fenced by the router (death, failed health, or wedge): "
    "the replica was gated out and drained; any survivors it carried "
    "replay elsewhere (fleet.replays_total counts those)",
)
_m_replays = _counter(
    "fleet.replays_total",
    "Requests resubmitted to another replica after a replica death "
    "(recompute-style: emitted tokens folded into the prompt)",
)
_m_rep_pages = _gauge(
    "fleet.replica_pages_in_use",
    "KV pages owned by live sequences, per replica",
    labels=("replica",),
)
_m_rep_queue = _gauge(
    "fleet.replica_queue_depth",
    "Admission-queue depth, per replica",
    labels=("replica",),
)
_m_placements = _counter(
    "fleet.placements_total",
    "Requests placed by the router, by chosen replica",
    labels=("replica",),
)
_m_tier_replicas = _gauge(
    "fleet.tier_replicas_active",
    "Replicas currently accepting placements, by tier role "
    "(prefill / decode / mixed — see serve/tiers.py)",
    labels=("tier",),
)

#: session-affinity map bound: beyond this many distinct sessions the
#: oldest mapping is forgotten (affinity is an optimization, not a
#: correctness property — a forgotten session just re-places least-loaded)
_MAX_SESSIONS = 4096


class FleetHandle(GenerationHandle):
    """The caller's end of one FLEET request: the same streaming surface
    as :class:`~.scheduler.GenerationHandle` (iterate for tokens,
    :meth:`result` for the full generation), fed by the router's relay —
    tokens keep flowing across replica failovers, and the stream is
    byte-identical to a solo decode whether zero or several replicas
    died underneath it."""

    def _finish(self, error: Optional[BaseException] = None) -> None:
        # idempotent: a late engine-side close (e.g. fleet stop racing a
        # replica's own shutdown sweep) must not clobber the first result
        if self._done.is_set():
            return
        super()._finish(error)


class _FleetRequest:
    """The router's replay record for one request: everything needed to
    resubmit it recompute-style, plus the live relay identity."""

    __slots__ = (
        "request_id", "prompt", "max_new_tokens", "temperature", "top_p",
        "seed", "eos_id", "deadline_t", "session", "handle", "replica",
        "inner", "replays", "last_error", "lock", "parked_t", "trace",
        "tenant",
    )

    def __init__(
        self,
        request_id: int,
        prompt: np.ndarray,
        max_new_tokens: int,
        temperature: float,
        top_p: float,
        seed: int,
        eos_id: Optional[int],
        deadline_t: Optional[float],
        session: Optional[str],
        handle: FleetHandle,
        tenant: str = "",
    ):
        self.request_id = request_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_p = top_p
        self.seed = seed
        self.eos_id = eos_id
        self.deadline_t = deadline_t
        self.session = session
        self.handle = handle
        self.tenant = tenant
        self.replica: Optional["_Replica"] = None
        self.inner: Optional["_RelayHandle"] = None
        self.replays = 0
        self.last_error: Optional[BaseException] = None
        #: serializes the relay identity gate against detach+snapshot in
        #: ``_submit_to`` — without it, a wedged replica's thread could
        #: pass the gate, stall, and forward its token AFTER the replay
        #: snapshot (a duplicated position on the client stream)
        self.lock = threading.Lock()
        #: monotonic time this record entered the failover queue (reset
        #: each death); bounds how long a survivor may wait for a
        #: healthy replica before failing fail-fast-style
        self.parked_t: Optional[float] = None
        #: the request's TraceContext: one trace_id across EVERY replica
        #: that serves it — each replay adds a ``fleet.replay`` event
        #: with a ``replay=N`` attribute to the same trace
        self.trace = None


class _RelayHandle(GenerationHandle):
    """The engine-side handle the router submits on a request's behalf:
    emissions forward to the caller's :class:`FleetHandle`, and the
    terminal close reports back to the fleet so a replica death turns
    into a replay instead of a failed stream. Forwarding is gated on
    relay IDENTITY (``rec.inner is self``) so a stale relay — a wedged
    replica waking up after its request was already replayed — cannot
    corrupt the stream with duplicate tokens or a stale close."""

    def __init__(self, request_id: int, fleet: "Fleet", rec: _FleetRequest):
        super().__init__(request_id)
        self._fleet = fleet
        self._rec = rec
        # the engine writes its timing breakdown to the handle IT holds
        # (this relay); sharing the dict object makes those writes land
        # on the caller's FleetHandle — and accumulate across replays
        self.timings = rec.handle.timings
        with rec.lock:
            rec.inner = self

    def _emit(self, token: int) -> None:
        super()._emit(token)
        # gate check and forward under the record lock: a bare
        # check-then-forward could pass the gate, stall, and deliver
        # after a replay snapshot — the duplicated-position corruption
        # the gate exists to prevent
        first = len(self._tokens) == 1
        with self._rec.lock:
            if self._rec.inner is self:
                self._rec.handle._emit(token)
            else:
                first = False
        if first and not self._done.is_set():
            # first live token from THIS relay: on a prefill-tier
            # replica that is the handoff point — prefill work is done,
            # every decode step from here on belongs on the decode
            # tier. Enqueue only; the router tick does the migration
            # (this runs on the engine's stepping thread, step lock
            # held — it must stay O(1)).
            self._fleet._maybe_handoff(self._rec)

    def _finish(self, error: Optional[BaseException] = None) -> None:
        super()._finish(error)
        self._fleet._on_inner_finish(self._rec, self, error)


class _StreamComplete(Exception):
    """Raised by ``_submit_to`` when the locked snapshot shows the
    stream already delivered its whole budget (or its EOS): there is
    nothing left to resubmit — the caller settles the handle as
    SUCCESS. Internal control flow, never caller-visible."""


class _Replica:
    """One engine plus its gate state. ``active`` replicas accept
    placements; ``fenced`` ones are draining/restarting; ``draining``
    ones finish their in-flight streams but take no NEW placements (the
    rolling-restart / graceful-shutdown gate — an administrative state,
    not a failure). ``wedged`` marks a fence whose stepping thread never
    exited (a stuck device call) — auto-restart skips those, since
    ``restart()`` would block on the lock the wedged step still holds;
    recycle the process."""

    __slots__ = (
        "name", "engine", "state", "wedged", "restarting", "lock", "tier",
    )

    def __init__(self, name: str, engine: GenerationEngine, tier: str = "mixed"):
        self.name = name
        self.engine = engine
        self.state = "active"
        self.wedged = False
        self.restarting = False
        self.lock = threading.Lock()
        #: placement role (``serve/tiers.py``): ``prefill`` replicas take
        #: new requests and hand streams off at first token; ``decode``
        #: replicas receive migrated streams; ``mixed`` (the default) does
        #: both — a fleet of all-mixed replicas behaves exactly as before
        #: tiering existed.
        self.tier = tier


class Fleet:
    """N :class:`GenerationEngine` replicas behind one admission router.

    >>> fleet = Fleet(lm, replicas=3, max_slots=8, page_size=16)
    >>> with fleet:                      # engines + watchdog threads
    ...     h = fleet.submit(prompt_ids, max_new_tokens=64, session="u1")
    ...     for tok in h: ...            # survives replica deaths
    >>> fleet.generate([p1, p2], 32)     # convenience, like the engine's

    Engine-construction keywords (``max_slots``, ``page_size``,
    ``num_pages``, ``max_seq_len``, ``queue_capacity``, ``top_k``,
    ``eos_id``, ``moe_top_k``) apply to every replica — identical
    replicas are what make replay byte-identical. Fleet knobs:

    - ``watchdog_interval_s`` — health-poll + failover-drain cadence;
    - ``wedge_timeout_s`` — last-step watchdog age (with work pending)
      past which a live-but-stuck replica is fenced;
    - ``probe_timeout_s`` — how long a restarted replica's probe
      generation may take before re-admission is abandoned (retried on
      a later poll);
    - ``max_replays`` — per-request failover budget (a poison request
      that deterministically kills replicas is failed, not bounced
      forever);
    - ``failover_timeout_s`` — how long a survivor of a replica death
      may wait parked for a healthy replica (every replica fenced,
      restarts failing) before its handle fails with the replica's
      error — the fleet's version of the fail-fast rule that a doomed
      stream's consumer must never hang to its own timeout;
    - ``auto_restart`` — False leaves fenced replicas down until a
      caller restarts + probes them (``restart_replica()``);
    - ``replica_kwargs`` — per-replica engine-kwarg overrides (one dict
      per replica, merged over the shared kwargs). The tensor-parallel
      door: replicas of different TP degree (``mesh=...``) coexist
      behind one router, and failover replay ACROSS degrees stays
      byte-identical because every degree emits the same bytes
      (``serve/tp.py``);
    - ``engines`` — pre-built ``(name, engine)`` pairs instead of a
      model + construction kwargs: the elastic-membership door
      (``serve/membership.py``) where the router fronts remote-replica
      adapters it did not construct and the roster grows/shrinks at
      runtime as members register and resign;
    - ``tiers`` — one role label per replica (``prefill`` / ``decode``
      / ``mixed``): the disaggregated-serving door (``serve/tiers.py``).
      New requests place on prefill-capable replicas and migrate to the
      decode tier at first token via live KV-page handoff; all-``mixed``
      (the default) is the monolithic fleet, byte-for-byte.
    """

    def __init__(
        self,
        model=None,
        *,
        replicas: int = 2,
        watchdog_interval_s: float = 0.05,
        wedge_timeout_s: float = 30.0,
        probe_timeout_s: float = 30.0,
        max_replays: int = 8,
        failover_timeout_s: float = 60.0,
        auto_restart: bool = True,
        replica_kwargs: Optional[Sequence[Dict]] = None,
        engines: Optional[Sequence[Tuple[str, object]]] = None,
        tiers: Optional[Sequence[str]] = None,
        **engine_kwargs,
    ):
        if engines is not None:
            # pre-built engine injection — the elastic-membership door
            # (serve/membership.py): the router fronts engines it did
            # NOT construct (remote-replica adapters, an empty roster
            # that fills as members register). Construction kwargs are
            # meaningless here, so mixing the modes is a caller bug.
            if model is not None or replica_kwargs is not None or engine_kwargs:
                raise ValueError(
                    "engines= is mutually exclusive with model/"
                    "replica_kwargs/engine construction kwargs — the "
                    "injected engines are already built"
                )
            names = [str(n) for n, _ in engines]
            if len(set(names)) != len(names):
                raise ValueError(f"duplicate replica names in engines=: {names}")
        elif model is None:
            raise ValueError("need a model (or pre-built engines=)")
        elif replicas < 1:
            raise ValueError(f"need replicas >= 1; got {replicas}")
        if replica_kwargs is not None:
            if len(replica_kwargs) != replicas:
                raise ValueError(
                    f"replica_kwargs has {len(replica_kwargs)} entries "
                    f"for {replicas} replicas — one override dict per "
                    f"replica"
                )
            for i, kw in enumerate(replica_kwargs):
                reserved = {"name", "model"} & set(kw)
                if reserved:
                    # replica names are fleet-owned (the cost registry
                    # and /statusz key on them) and the model is the
                    # positional argument — a collision would surface
                    # as an opaque TypeError from engine construction
                    raise ValueError(
                        f"replica_kwargs[{i}] overrides fleet-owned "
                        f"key(s) {sorted(reserved)}; replica names are "
                        f"assigned by the fleet and the model is shared"
                    )
        # replica names flow into each engine so the per-program cost
        # registry (obs/programs.py) and /statusz attribute every step
        # program to its replica (serve.decode[r1], ...).
        #
        # ``replica_kwargs`` overlays PER-REPLICA engine kwargs on the
        # shared ones — the heterogeneous-fleet door: replicas of
        # DIFFERENT tensor-parallel degree (``mesh=...``) behind one
        # router. Byte-identity makes that safe: every TP degree emits
        # the same bytes for the same request (serve/tp.py), so failover
        # replay across degrees stays invisible to the stream exactly
        # like same-shape failover. Overrides that change emitted
        # streams (the model, top_k, eos_id) are the caller's contract
        # to keep identical, as ever.
        #
        # ``self._replicas`` is rebound copy-on-write (never mutated in
        # place) so the router's lock-free sweeps iterate a consistent
        # snapshot while members join and leave (:meth:`_add_replica` /
        # :meth:`_remove_replica`).
        if engines is not None:
            self._replicas: List[_Replica] = [
                _Replica(str(name), eng) for name, eng in engines
            ]
        else:
            self._replicas = [
                _Replica(
                    f"r{i}",
                    GenerationEngine(
                        model,
                        name=f"r{i}",
                        **{
                            **engine_kwargs,
                            **(
                                replica_kwargs[i]
                                if replica_kwargs is not None
                                else {}
                            ),
                        },
                    ),
                )
                for i in range(int(replicas))
            ]
        if tiers is not None:
            # one tier label per replica, roster order — the
            # disaggregated-serving door (serve/tiers.py): ``prefill``
            # replicas take new requests and hand each stream off at
            # first token; ``decode`` replicas receive the migrated
            # streams. All-``mixed`` (the default) is the monolithic
            # fleet, byte-for-byte.
            if len(tiers) != len(self._replicas):
                raise ValueError(
                    f"tiers= has {len(tiers)} labels for "
                    f"{len(self._replicas)} replicas — one per replica"
                )
            for t in tiers:
                if t not in TIERS:
                    raise ValueError(
                        f"unknown tier {t!r}; expected one of {TIERS}"
                    )
            for rep, t in zip(self._replicas, tiers):
                rep.tier = str(t)
        self.watchdog_interval_s = float(watchdog_interval_s)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.max_replays = int(max_replays)
        self.failover_timeout_s = float(failover_timeout_s)
        self.auto_restart = bool(auto_restart)
        self._lock = threading.Lock()
        self._id_lock = threading.Lock()
        self._req_counter = 0
        self._inflight: Dict[int, _FleetRequest] = {}
        self._pending: Deque[_FleetRequest] = deque()
        #: first-token handoff queue (serve/tiers.py): records whose
        #: stream just produced its first token on a ``prefill``-tier
        #: replica, awaiting migration to a decode-capable replica on
        #: the next router tick. Drained by :meth:`_drain_migrations`.
        self._handoff: Deque[_FleetRequest] = deque()
        #: pool-pressure rebalance queue: ``(snapshot, rec, dst_name)``
        #: triples detached synchronously by the on_pressure hook (on
        #: the source engine's stepping thread) and imported
        #: asynchronously here — the split keeps the source step lock
        #: and the destination step lock from ever nesting.
        self._imports: Deque[Tuple[object, _FleetRequest, str]] = deque()
        #: session key -> (pinned replica, tenant) — the tenant rides
        #: along so the SLO actuator can drop one tenant's pins
        #: (:meth:`replace_tenant_sessions`) without scanning requests
        self._sessions: "OrderedDict[str, Tuple[_Replica, str]]" = (
            OrderedDict()
        )
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._wake = threading.Event()
        self._closed = False
        #: callables run once per router tick (after health polling,
        #: before the failover drain) — the membership layer's sync
        #: point: registry scans, autoscaler evaluation. A hook that
        #: raises is logged and kept; it must not kill the watchdog.
        self._tick_hooks: List = []
        #: the router-election epoch this fleet places under (None =
        #: router HA not attached → no fencing header on remote
        #: placements, the pre-HA wire format). Set by
        #: ``serve/router_ha.py`` when this process wins the router
        #: lease; deliberately LEFT at the stale value after a lease
        #: loss so a zombie router's placements carry the superseded
        #: epoch and members reject them (StaleRouterEpochError).
        self.router_epoch: Optional[int] = None
        _m_replicas_healthy.set(float(len(self._replicas)))

    # -- introspection -----------------------------------------------------

    @property
    def engines(self) -> List[GenerationEngine]:
        """The replica engines, placement order (benches warm each one)."""
        return [rep.engine for rep in self._replicas]

    @property
    def replica_names(self) -> List[str]:
        return [rep.name for rep in self._replicas]

    def replica_state(self, name: str) -> str:
        return self._replica(name).state

    def _replica(self, name: str) -> _Replica:
        for rep in self._replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r}")

    def program_counts(self) -> Dict[str, int]:
        """Compiled step programs per replica — the soak pins every value
        at <= 3 (<= 5 for speculative replicas); failover, fencing,
        restart, and probe are all shape-static."""
        return {
            rep.name: rep.engine.num_step_programs for rep in self._replicas
        }

    def health(self) -> Dict[str, object]:
        """Aggregate liveness for ``GET /healthz``: 200-worthy while ANY
        replica serves, with per-replica detail (each replica's engine
        snapshot plus its gate state) for operators and the soak."""
        reps: Dict[str, object] = {}
        healthy = 0
        queue_depth = active = pages = cap = 0
        for rep in self._replicas:
            h = rep.engine.health()
            h["state"] = rep.state
            h["wedged"] = rep.wedged
            h["tier"] = rep.tier
            reps[rep.name] = h
            if rep.state == "active" and h["healthy"]:
                healthy += 1
            queue_depth += h["queue_depth"]
            active += h["active_slots"]
            pages += h["pages_in_use"]
            cap += h["pages_capacity"]
        return {
            "healthy": healthy > 0,
            "replicas_total": len(self._replicas),
            "replicas_healthy": healthy,
            "queue_depth": queue_depth,
            "active_slots": active,
            "pages_in_use": pages,
            "pages_capacity": cap,
            "inflight_requests": len(self._inflight),
            "replicas": reps,
        }

    # -- placement ---------------------------------------------------------

    @staticmethod
    def _tenant_slots(rep: _Replica, tenant: str) -> int:
        """This tenant's live decode slots on one replica (lock-free
        sweep of the slot list — the same stale-tolerant read the
        pages_free/queue_depth placement keys already are)."""
        return sum(
            1
            for a in rep.engine.scheduler.slots
            if a is not None and a.req.tenant == tenant
        )

    def _candidates(
        self,
        session: Optional[str] = None,
        tenant: Optional[str] = None,
        role: str = "new",
    ) -> List[_Replica]:
        """Active, healthy replicas in placement-preference order:
        session-affine replica first (when mapped and still eligible),
        then least-loaded — most free KV pages, then shallowest queue,
        then name (a deterministic tiebreak). With the QoS plane on and
        a tenant named, replicas holding FEWER of that tenant's active
        slots come first (ahead of raw load): one tenant's flood piles
        onto the replicas it already occupies instead of spreading to
        monopolize every pool.

        ``role`` applies the tier preference (serve/tiers.py) as the
        LEADING sort key — a soft preference, never a filter, so a
        fleet whose preferred tier is entirely fenced degrades to
        placing on whatever is healthy rather than shedding:

        - ``"new"`` — fresh placements prefer ``prefill`` + ``mixed``
          replicas (prefill capacity is what new requests consume);
        - ``"decode"`` — migration targets prefer ``decode`` +
          ``mixed`` replicas.

        Raises :class:`EngineUnhealthyError` when every replica is
        fenced — the ALL-replicas-down shed the endpoint maps to 503."""
        _chaos.site("fleet.place")
        cands = [
            rep
            for rep in self._replicas
            if rep.state == "active"
            and rep.engine.healthy
            and not rep.engine._stop_wedged
        ]
        if not cands:
            raise EngineUnhealthyError(
                "all fleet replicas are fenced or unhealthy; the watchdog "
                "is restarting them — retry shortly"
            )
        preferred = (
            ("prefill", "mixed") if role == "new" else ("decode", "mixed")
        )

        def _tier_rank(rep: _Replica) -> int:
            return 0 if rep.tier in preferred else 1

        if tenant and _tenancy.enabled():
            cands.sort(
                key=lambda rep: (
                    _tier_rank(rep),
                    self._tenant_slots(rep, tenant),
                    -rep.engine.pool.pages_free,
                    rep.engine.scheduler.queue_depth,
                    rep.name,
                )
            )
        else:
            cands.sort(
                key=lambda rep: (
                    _tier_rank(rep),
                    -rep.engine.pool.pages_free,
                    rep.engine.scheduler.queue_depth,
                    rep.name,
                )
            )
        if session is not None:
            with self._lock:
                entry = self._sessions.get(session)
                if entry is not None:
                    self._sessions.move_to_end(session)
            sticky = entry[0] if entry is not None else None
            if sticky is not None and sticky in cands:
                cands.remove(sticky)
                cands.insert(0, sticky)
        return cands

    def _remember_session(
        self, session: str, rep: _Replica, tenant: str = ""
    ) -> None:
        with self._lock:
            self._sessions[session] = (rep, tenant)
            self._sessions.move_to_end(session)
            while len(self._sessions) > _MAX_SESSIONS:
                self._sessions.popitem(last=False)

    def replace_tenant_sessions(self, tenant: str) -> int:
        """Drop every session→replica pin whose traffic bills to
        ``tenant`` (the SLO actuator's sustained-burn re-placement):
        the tenant's NEXT requests place least-loaded instead of
        sticking to the replicas they saturated. In-flight streams are
        untouched — placement moves, bytes don't. Returns the number
        of pins dropped."""
        with self._lock:
            victims = [
                s for s, (_, t) in self._sessions.items() if t == tenant
            ]
            for s in victims:
                del self._sessions[s]
        if victims:
            _flight.record(
                "fleet", "replace_sessions", tenant=tenant,
                sessions=len(victims),
            )
        return len(victims)

    def tenant_counts(self) -> Tuple[dict, dict]:
        """Fleet-wide per-tenant footprint: active slots and queued
        requests summed across replicas (the QoS quota input and the
        ``/statusz`` per-tenant view)."""
        active: dict = {}
        queued: dict = {}
        for rep in self._replicas:
            a, q = rep.engine.scheduler.tenant_counts()
            for t, n in a.items():
                active[t] = active.get(t, 0) + n
            for t, n in q.items():
                queued[t] = queued.get(t, 0) + n
        return active, queued

    def _submit_to(self, rep: _Replica, rec: _FleetRequest) -> None:
        """One engine submission for ``rec`` on ``rep``, recompute-style:
        whatever the stream already delivered folds into the prompt and
        shrinks the budget, so the replica prefills ``prompt + emitted``
        and the relay emits only NEW tokens."""
        deadline = None
        if rec.deadline_t is not None:
            deadline = rec.deadline_t - time.monotonic()
            if deadline <= 0:
                raise DeadlineExceededError(
                    f"request {rec.request_id} exceeded its deadline "
                    f"before placement"
                )
        # detach any previous relay and snapshot progress ATOMICALLY
        # (rec.lock pairs with the gate in _RelayHandle._emit): a wedged
        # replica waking up after the snapshot must find the gate
        # closed, or its late emission would both reach the client and
        # be regenerated by the replay (a duplicated position)
        with rec.lock:
            rec.inner = None
            emitted = list(rec.handle._tokens)
        # the AUTHORITATIVE completeness check, on the locked snapshot: a
        # wedged replica's final emission can land after any earlier
        # lock-free check, leaving nothing to resubmit (max_new would be
        # 0) — or an EOS the replay must not generate past
        remaining = rec.max_new_tokens - len(emitted)
        eos = rec.eos_id if rec.eos_id is not None else rep.engine.eos_id
        if remaining <= 0 or (
            eos is not None and emitted and emitted[-1] == eos
        ):
            raise _StreamComplete()
        prompt = (
            np.concatenate([rec.prompt, np.asarray(emitted, np.int32)])
            if emitted
            else rec.prompt
        )
        rep.engine.submit(
            prompt,
            remaining,
            temperature=rec.temperature,
            top_p=rec.top_p,
            seed=rec.seed,
            eos_id=rec.eos_id,
            block=False,
            deadline=deadline,
            trace=rec.trace,
            tenant=rec.tenant,
            _handle_factory=lambda rid: _RelayHandle(rid, self, rec),
        )
        rec.replica = rep

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
        block: bool = True,
        timeout: Optional[float] = None,
        deadline: Optional[float] = None,
        session: Optional[str] = None,
        tenant: Optional[str] = None,
        _resume_tokens: Optional[Sequence[int]] = None,
    ) -> FleetHandle:
        """Place one request on a healthy replica; returns its streaming
        handle. Raises ``ValueError`` for infeasible requests (every
        replica is identical — rejected everywhere),
        :class:`QueueFullError` when every active replica's admission
        queue is full (``block=True`` waits up to ``timeout`` for room),
        and :class:`EngineUnhealthyError` when ALL replicas are fenced
        (the endpoint's 503). ``session`` pins subsequent requests with
        the same key to one replica while it stays healthy. ``tenant``
        labels the request's cost-attribution record
        (``obs/requests.py``); it defaults to the session key so
        session-affine traffic is attributable without extra plumbing.

        ``_resume_tokens`` (router-HA internal, ``serve/router_ha.py``)
        pre-seeds the handle with tokens a PREVIOUS router incarnation
        already delivered, so placement goes through the same
        recompute-style fold as a replica-death replay: the delivered
        prefix folds into the prompt, the budget shrinks, per-step
        sampling keys land at their absolute positions, and the stream
        stays byte-identical across the takeover. Such a resubmission
        skips the QoS admission gate — the request was admitted (and
        billed) by the incarnation that journaled it; a takeover must
        not re-charge or re-refuse it. A resume whose prefix already
        covers the budget (or ended at EOS) settles immediately as
        success."""
        if self._closed and self._thread is None:
            raise EngineUnhealthyError("fleet is stopped")
        if deadline is not None and deadline <= 0:
            # same client-error classification as GenerationEngine.submit
            # (a 400, not a 504-shaped DeadlineExceededError from the
            # placement path)
            raise ValueError(
                f"deadline must be positive seconds from now; got {deadline}"
            )
        if int(max_new_tokens) < 1:
            # validated here too (not just per-engine) so the placement
            # path can rely on a fresh record never being "complete"
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}"
            )
        prompt = np.asarray(prompt, np.int32).ravel()
        tenant_key = str(tenant if tenant is not None else (session or ""))
        if _tenancy.enabled() and _resume_tokens is None:
            # the fleet-wide QoS gate, charged ONCE here: the replica
            # engines skip their own check on the relay path
            # (_handle_factory set), so a request is never billed
            # twice, and failover replays never re-enter this method
            active, queued = self.tenant_counts()
            _tenancy.admit_request(
                tenant_key, int(max_new_tokens),
                active.get(tenant_key, 0), queued.get(tenant_key, 0),
            )
        with self._id_lock:
            self._req_counter += 1
            rid = self._req_counter
        rec = _FleetRequest(
            rid,
            prompt,
            int(max_new_tokens),
            float(temperature),
            float(top_p),
            int(seed),
            eos_id,
            None if deadline is None else time.monotonic() + float(deadline),
            session,
            FleetHandle(rid),
            tenant=tenant_key,
        )
        # one trace_id for the request's whole life, however many
        # replicas serve it (the HTTP handler installs the traceparent's
        # context around this call; a fresh submit inherits any ambient
        # trace the same way)
        rec.trace = _current_trace()
        if _resume_tokens is not None:
            # a takeover resubmission: the previous incarnation's
            # delivered watermark becomes the handle's emitted prefix,
            # and _submit_to's fold does the rest (prompt + prefix,
            # shrunken budget). Safe to append directly — no relay has
            # been attached yet, so nothing else touches the handle.
            rec.handle._tokens.extend(int(t) for t in _resume_tokens)
        t_end = None if timeout is None else time.monotonic() + timeout
        while True:
            cands = run_with_retries(
                lambda: self._candidates(session, tenant_key),
                what="fleet.place",
            )
            exhausted = None
            for rep in cands:
                try:
                    self._submit_to(rep, rec)
                except _StreamComplete:
                    # only reachable for a _resume_tokens submission
                    # (a fresh record never starts complete): the WAL
                    # prefix already covers the budget or ends at EOS
                    rec.handle._finish(None)
                    return rec.handle
                except QueueFullError as e:
                    exhausted = e
                    continue
                except EngineUnhealthyError:
                    continue  # raced a death this tick; try the next
                with self._lock:
                    # stop() may have closed the fleet between the entry
                    # guard and placement; registering now would hand
                    # back a handle nothing will ever step or fail
                    if self._closed:
                        rec.handle._finish(
                            RuntimeError(
                                "fleet stopped with the request in flight"
                            )
                        )
                        raise EngineUnhealthyError("fleet is stopped")
                    # a request can settle terminally (instant deadline
                    # sweep, replica death) before this registration —
                    # inserting after _terminal's pop would leak the
                    # record forever, so check under the same lock
                    if not rec.handle.done:
                        self._inflight[rid] = rec
                if session is not None:
                    self._remember_session(session, rep, tenant_key)
                _m_placements.inc(replica=rep.name)
                return rec.handle
            if exhausted is None:
                # every candidate raced into a death mid-attempt (no
                # queue was actually full): re-resolve — the next
                # _candidates() sees their unhealthy flags and either
                # finds survivors or sheds EngineUnhealthyError, the
                # honest signal for "fleet down", not QueueFullError
                continue
            if not block:
                raise QueueFullError(
                    f"admission queues of all {len(cands)} active "
                    f"replica(s) are full"
                ) from exhausted
            if t_end is not None and time.monotonic() >= t_end:
                raise QueueFullError(
                    f"admission queues still full after {timeout}s"
                ) from exhausted
            # bounded poll rather than a cross-engine condition: this
            # path only spins while EVERY replica's queue is full (total
            # saturation), and queue drains happen inside N independent
            # engine locks that have no shared signal to wait on
            time.sleep(0.005)

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
        **kw,
    ) -> List[np.ndarray]:
        """Submit every prompt, wait for completion, return each
        request's generated tokens — the fleet twin of
        :meth:`GenerationEngine.generate`. Starts the fleet for the call
        when it is not already running."""
        started_here = self._thread is None
        if started_here:
            self.start()
        try:
            handles = [self.submit(p, max_new_tokens, **kw) for p in prompts]
            timeout = get_config().serve_result_timeout_s
            return [h.result(timeout=timeout) for h in handles]
        finally:
            if started_here:
                self.stop()

    # -- failover ----------------------------------------------------------

    @staticmethod
    def _replayable(error: BaseException) -> bool:
        """Replica deaths replay; the request's own terminal conditions
        do not: a passed deadline is passed everywhere, an infeasible
        request (``ValueError``) is infeasible on every identical
        replica, and a QoS throttle (``TenantThrottledError``) refused
        the TENANT — replaying would re-run work the admission gate
        rejected. A stale-epoch rejection (``StaleLeaseError`` /
        ``StaleRouterEpochError``) means THIS router was fenced — a
        member refusing a zombie's placement refuses it everywhere, so
        replaying would only hammer survivors with writes the fence
        exists to reject."""
        return not isinstance(
            error,
            (
                DeadlineExceededError, ValueError, TenantThrottledError,
                StaleLeaseError,
            ),
        )

    def _on_inner_finish(
        self,
        rec: _FleetRequest,
        inner: "_RelayHandle",
        error: Optional[BaseException],
    ) -> None:
        """A relay closed (engine thread context — keep this cheap and
        lock-light): success and non-replayable errors settle the
        caller's handle; replica deaths park the record for the router
        thread to resubmit."""
        with rec.lock:
            if rec.inner is not inner:
                return  # stale relay from before a replay
        if error is None:
            rec.handle._finish(None)
            with self._lock:
                self._inflight.pop(rec.request_id, None)
            return
        if (
            self._closed
            or not self._replayable(error)
            or rec.replays >= self.max_replays
        ):
            if rec.replays >= self.max_replays and self._replayable(error):
                logger.warning(
                    "fleet: request %d spent its replay budget (%d); "
                    "failing it with the replica's error",
                    rec.request_id,
                    self.max_replays,
                )
            self._terminal(rec, error)
            return
        rec.last_error = error
        rec.parked_t = time.monotonic()
        with self._lock:
            self._pending.append(rec)
        self._wake.set()

    def _terminal(self, rec: _FleetRequest, error: BaseException) -> None:
        rec.handle._finish(error)
        with self._lock:
            self._inflight.pop(rec.request_id, None)

    def _stream_complete(self, rec: _FleetRequest) -> bool:
        """Whether the stream already delivered everything the request
        asked for — the full budget, or its (request- or engine-level)
        EOS token. A replica can die in the window between a relay's
        final emission and its clean close (the wedged drain path);
        resubmitting such a request would either be infeasible
        (``max_new_tokens=0``) or generate PAST the EOS, so the router
        settles it as success instead."""
        emitted = rec.handle._tokens
        if len(emitted) >= rec.max_new_tokens:
            return True
        eos = rec.eos_id
        if eos is None:
            reps = self._replicas  # snapshot: the roster may be elastic
            eos = reps[0].engine.eos_id if reps else None
        return eos is not None and bool(emitted) and emitted[-1] == eos

    def _replay(self, rec: _FleetRequest) -> bool:
        """Resubmit one survivor of a replica death. True when settled
        (placed, terminally failed, or recognized as already complete);
        False parks it for the next tick (no healthy replica with queue
        room right now)."""
        if rec.handle.done:
            with self._lock:
                self._inflight.pop(rec.request_id, None)
            return True
        if self._stream_complete(rec):
            rec.handle._finish(None)
            with self._lock:
                self._inflight.pop(rec.request_id, None)
            return True
        try:
            cands = run_with_retries(
                lambda: self._candidates(rec.session), what="fleet.place"
            )
        except EngineUnhealthyError:
            return False  # everything fenced; restarts are in flight
        except Exception as e:
            self._terminal(rec, e)
            return True
        for rep in cands:
            try:
                self._submit_to(rep, rec)
            except _StreamComplete:
                # a late (gated) final emission landed after the
                # lock-free pre-check: the consumer already has every
                # byte — settle success, nothing to resubmit
                rec.handle._finish(None)
                with self._lock:
                    self._inflight.pop(rec.request_id, None)
                return True
            except (QueueFullError, EngineUnhealthyError):
                continue
            except Exception as e:
                self._terminal(rec, e)
                return True
            rec.replays += 1
            _m_replays.inc()
            rec.handle.timings["replays"] = rec.replays
            # a new span in the SAME trace marks the failover point: the
            # replayed request's prefill/decode spans on the new replica
            # carry the same trace_id, so the whole story is one trace
            with _use_trace(rec.trace):
                _trace_event(
                    "fleet.replay",
                    request=rec.request_id,
                    replica=rep.name,
                    replay=rec.replays,
                    emitted=len(rec.handle._tokens),
                    error=type(rec.last_error).__name__,
                )
            _flight.record(
                "fleet", "replay", request=rec.request_id,
                replica=rep.name, replay=rec.replays,
            )
            logger.warning(
                "fleet: request %d replayed on replica %s after %s "
                "(%d emitted token(s) folded into the prompt)",
                rec.request_id,
                rep.name,
                type(rec.last_error).__name__,
                len(rec.handle._tokens),
            )
            return True
        return False

    def _drain_failovers(self) -> None:
        with self._lock:
            batch = list(self._pending)
            self._pending.clear()
        parked: List[_FleetRequest] = []
        now = time.monotonic()
        # the fail-fast timer below measures time with NO healthy replica
        # — waiting behind FULL queues on a healthy fleet is ordinary
        # backpressure, not doom, so presence of healthy capacity resets
        # the clock instead of failing the survivor with a stale error
        fleet_has_healthy = any(
            rep.state == "active"
            and rep.engine.healthy
            and not rep.engine._stop_wedged
            for rep in self._replicas
        )
        for rec in batch:
            if (
                rec.deadline_t is not None
                and now >= rec.deadline_t
                and not rec.handle.done
            ):
                self._terminal(
                    rec,
                    DeadlineExceededError(
                        f"request {rec.request_id} exceeded its deadline "
                        f"awaiting failover"
                    ),
                )
                continue
            if fleet_has_healthy:
                rec.parked_t = now
            elif (
                rec.parked_t is not None
                and now - rec.parked_t > self.failover_timeout_s
                and not rec.handle.done
            ):
                # the fail-fast rule, fleet edition: with every replica
                # fenced and restarts not landing, a deadline-less
                # consumer must get the replica's real error rather
                # than hang to its own (or no) timeout
                logger.warning(
                    "fleet: request %d waited %.1fs for a healthy "
                    "replica; failing it with the replica's error",
                    rec.request_id,
                    now - rec.parked_t,
                )
                self._terminal(
                    rec,
                    rec.last_error
                    or EngineUnhealthyError(
                        "no healthy replica within the failover timeout"
                    ),
                )
                continue
            if not self._replay(rec):
                parked.append(rec)
        if parked:
            with self._lock:
                self._pending.extendleft(reversed(parked))

    # -- live KV-page migration (serve/tiers.py) ---------------------------

    def _maybe_handoff(self, rec: _FleetRequest) -> None:
        """Queue ``rec`` for tier handoff if its stream just produced
        its first token on a ``prefill``-tier replica. Called from the
        relay's ``_emit`` — the SOURCE engine's stepping thread, step
        lock held — so this only enqueues; the router tick migrates."""
        if self._closed or not get_config().tier_handoff:
            return
        rep = rec.replica
        if rep is None or rep.tier != "prefill":
            return
        with self._lock:
            self._handoff.append(rec)
        self._wake.set()

    def _drain_migrations(self) -> None:
        """One router tick's worth of migrations: first-token handoffs
        off prefill replicas, then pool-pressure rebalance imports
        parked by the on_pressure hook."""
        with self._lock:
            handoffs = list(self._handoff)
            self._handoff.clear()
            imports = list(self._imports)
            self._imports.clear()
        for rec in handoffs:
            try:
                self._migrate_handoff(rec)
            except Exception:
                logger.exception(
                    "fleet: handoff of request %d failed unexpectedly",
                    rec.request_id,
                )
        for snap, rec, dst_name in imports:
            try:
                self._import_slot(snap, rec, dst_name)
            except Exception:
                logger.exception(
                    "fleet: rebalance import of request %d failed "
                    "unexpectedly",
                    rec.request_id,
                )

    def _migrate_handoff(self, rec: _FleetRequest) -> None:
        """Move one just-prefilled stream from its prefill-tier replica
        to a decode-capable one: export the slot's KV pages (host
        bytes), retire the source relay, restore on the destination.
        Failure BEFORE the export is a no-op (the stream keeps decoding
        where it is); failure AFTER falls back to the recompute-style
        replay path — the same ladder a replica death uses — so the
        caller's stream survives either way, byte-identical."""
        src = rec.replica
        inner = rec.inner
        if (
            rec.handle.done
            or src is None
            or src.tier != "prefill"
            or inner is None
            or not hasattr(src.engine, "detach_slot")
        ):
            return
        try:
            # the chaos window for the fleet-level migration decision;
            # transient faults retry invisibly, a fatal one aborts the
            # handoff before any pages moved (stream unaffected)
            run_with_retries(
                lambda: _chaos.site("fleet.migrate"), what="fleet.migrate"
            )
            dsts = [
                rep
                for rep in self._candidates(
                    rec.session, rec.tenant or None, role="decode"
                )
                if rep is not src and hasattr(rep.engine, "attach_slot")
            ]
            if not dsts:
                return  # no decode capacity: keep decoding on prefill
            snap = src.engine.detach_slot(inner.request_id, reason="handoff")
        except Exception as e:
            # nothing was detached — the slot still lives at the source
            # and keeps streaming; log and count the aborted attempt
            _tiers._m_migrations.inc(reason="aborted")
            logger.warning(
                "fleet: handoff of request %d aborted before export "
                "(%s: %s); stream continues on %s",
                rec.request_id, type(e).__name__,
                str(e).split("\n", 1)[0][:120], src.name,
            )
            return
        if snap is None:
            return  # already finished / preempted / not migratable
        self._place_snapshot(snap, rec, inner, dsts, reason="handoff")

    def _import_slot(self, snap, rec: _FleetRequest, dst_name: str) -> None:
        """Land one rebalance snapshot (detached synchronously by the
        on_pressure hook) on its chosen destination, least-loaded
        fallbacks behind it."""
        inner = rec.inner
        try:
            dsts = [
                rep
                for rep in self._candidates(
                    rec.session, rec.tenant or None, role="decode"
                )
                if rep.name != snap.source
                and hasattr(rep.engine, "attach_slot")
            ]
        except EngineUnhealthyError:
            dsts = []
        # the hook's chosen destination goes first if still eligible
        dsts.sort(key=lambda rep: rep.name != dst_name)
        self._place_snapshot(snap, rec, inner, dsts, reason="rebalance")

    def _place_snapshot(
        self,
        snap,
        rec: _FleetRequest,
        inner: Optional[_RelayHandle],
        dsts: List[_Replica],
        reason: str,
    ) -> None:
        """The import half of a migration: the snapshot's pages are OFF
        the source (freed), so the stream MUST land somewhere — try
        each destination, and when none takes it, fall back to the
        replay queue (recompute-style, same as a replica death). The
        source relay is retired first so a stale late emission from the
        source engine cannot race the destination's stream."""
        with rec.lock:
            if rec.inner is inner:
                rec.inner = None
        for dst in dsts:
            try:
                dst.engine.attach_slot(
                    snap,
                    _handle_factory=lambda rid: _RelayHandle(rid, self, rec),
                )
            except Exception as e:
                logger.warning(
                    "fleet: migration of request %d to %s failed (%s: "
                    "%s); trying next destination",
                    rec.request_id, dst.name, type(e).__name__,
                    str(e).split("\n", 1)[0][:120],
                )
                continue
            rec.replica = dst
            if rec.session is not None:
                self._remember_session(rec.session, dst, rec.tenant)
            with _use_trace(rec.trace):
                _trace_event(
                    "fleet.migrate",
                    request=rec.request_id,
                    source=snap.source,
                    replica=dst.name,
                    reason=reason,
                    pages=snap.n_pages,
                    emitted=len(rec.handle._tokens),
                )
            _flight.record(
                "fleet", "migrate", request=rec.request_id,
                source=snap.source, replica=dst.name, reason=reason,
                pages=snap.n_pages,
            )
            logger.info(
                "fleet: request %d migrated %s -> %s (%s, %d page(s), "
                "%d token(s) emitted)",
                rec.request_id, snap.source, dst.name, reason,
                snap.n_pages, len(rec.handle._tokens),
            )
            return
        # no destination took the pages — recompute-style fallback:
        # park the record for the ordinary replay drain (prompt + the
        # tokens already emitted re-prefill elsewhere, byte-identical)
        _tiers._m_migrations.inc(reason="failed")
        rec.last_error = TierMigrationError(
            f"no destination accepted the migrated pages of request "
            f"{rec.request_id}; replaying recompute-style"
        )
        rec.parked_t = time.monotonic()
        logger.warning(
            "fleet: migration of request %d found no destination; "
            "falling back to recompute replay", rec.request_id,
        )
        with self._lock:
            self._pending.append(rec)
        self._wake.set()

    def _on_pool_pressure(self, rep: _Replica, victim_idx: int) -> bool:
        """The scheduler's ``on_pressure`` hook (serve/tiers.py door):
        under KV-pool pressure on ``rep``, try to MIGRATE the chosen
        victim's slot to a less-loaded decode-capable replica instead
        of preempting it. Runs on the source engine's stepping thread
        with the (re-entrant) step lock held: the export is synchronous
        (it frees the victim's pages, which is the whole point — the
        caller retries its reservation on True), but the import is
        parked for the router tick so the two engines' step locks never
        nest. Returns False for ANY reason migration can't proceed —
        the grow ladder falls back to preemption, exactly as before."""
        if (
            self._closed
            or self._thread is None
            or not get_config().tier_rebalance
        ):
            return False
        eng = rep.engine
        act = eng.scheduler.slots[victim_idx]
        if act is None or not act.generated or act.cow_src is not None:
            return False
        rec = getattr(act.req.handle, "_rec", None)
        if rec is None or rec.handle.done or rec.inner is not act.req.handle:
            return False
        need = len(act.seq.pages)
        try:
            cands = [
                r
                for r in self._candidates(None, None, role="decode")
                if r is not rep
                and hasattr(r.engine, "attach_slot")
                and r.engine.page_size == eng.page_size
                and r.engine.pool.pages_free > need
                and any(s is None for s in r.engine.scheduler.slots)
            ]
        except EngineUnhealthyError:
            return False
        if not cands:
            return False
        try:
            run_with_retries(
                lambda: _chaos.site("fleet.migrate"), what="fleet.migrate"
            )
            snap = eng.detach_slot(act.req.request_id, reason="rebalance")
        except Exception as e:
            logger.warning(
                "fleet: rebalance export on %s aborted (%s); preempting "
                "instead", rep.name, type(e).__name__,
            )
            return False
        if snap is None:
            return False
        with self._lock:
            self._imports.append((snap, rec, cands[0].name))
        self._wake.set()
        logger.info(
            "fleet: pool pressure on %s — slot %d (request %d) exported "
            "for rebalance to %s instead of preemption",
            rep.name, victim_idx, rec.request_id, cands[0].name,
        )
        return True

    def _install_pressure_hook(self, rep: _Replica) -> None:
        """Point ``rep``'s scheduler at the fleet's migrate-not-preempt
        ladder rung. Local engines only — a remote-replica adapter has
        no scheduler here (its own process installs its own hook)."""
        sched = getattr(rep.engine, "scheduler", None)
        if sched is None or not hasattr(rep.engine, "detach_slot"):
            return
        sched.on_pressure = (
            lambda victim_idx, _rep=rep: self._on_pool_pressure(
                _rep, victim_idx
            )
        )

    # -- health gating -----------------------------------------------------

    def _fence(
        self, rep: _Replica, error: BaseException, wedged: bool = False
    ) -> None:
        """Gate a replica out: no new placements, and every attached
        handle fails NOW so its survivors hit the failover queue instead
        of hanging against an engine that will never step them. A
        ``draining`` replica fences too — an administrative drain does
        not immunize a replica against dying, and its in-flight streams
        still deserve the replay path."""
        with rep.lock:
            if rep.state not in ("active", "draining"):
                return
            rep.state = "fenced"
            rep.wedged = wedged
        _m_failovers.inc()
        _flight.record(
            "fleet", "fence", replica=rep.name, wedged=wedged,
            error=f"{type(error).__name__}: {_first_line(error)}",
        )
        logger.warning(
            "fleet: replica %s fenced (%s: %s); draining%s",
            rep.name,
            type(error).__name__,
            str(error).split("\n", 1)[0][:120],
            "" if wedged else " and restarting in the background",
        )
        eng = rep.engine
        eng.healthy = False  # submit sheds immediately, before the drain
        try:
            if wedged:
                # the wedged step may hold the step lock forever; fail the
                # handles through the scheduler directly rather than
                # blocking the watchdog behind a stuck device call
                eng.scheduler.fail_all(error)
            elif eng._thread is not None and eng._thread.is_alive():
                # a live stepping loop drains ITSELF at the next step
                # boundary — fighting it for the step lock from here
                # could lose for many steps while the doomed engine
                # keeps emitting
                eng.inject_fault(error)
            else:
                eng._fail_inflight(error)  # nothing stepping: drain inline
        except Exception:
            logger.warning(
                "fleet: drain of replica %s failed", rep.name, exc_info=True
            )
        self._wake.set()

    def _kill_replica(self, rep: _Replica, error: BaseException) -> None:
        """A chaos-scheduled hard replica fault: the replica dies at its
        next step boundary (fence + injected fault), then its device
        state is scrambled outright (like the crash drills in
        tests/test_chaos.py) — the router must carry every stream
        without the dead replica's help, and ``restart()`` must not
        depend on anything the pool still holds."""
        self._fence(rep, error)
        eng = rep.engine
        # scramble only AFTER the injected fault drained at a step
        # boundary: a step already past the poison check may not have
        # read pool.k/v yet, and scrambling under it would emit wrong
        # bytes through the still-open relay before the kill lands
        drained = time.monotonic() + 2.0
        while eng._poison is not None and time.monotonic() < drained:
            time.sleep(0.002)
        if eng._poison is not None:
            # a step is stuck past the poison check: scrambling under it
            # would be the exact corrupt-emission this wait prevents —
            # the fence (and eventual drain) IS the kill; skip the color
            logger.warning(
                "fleet: replica %s kill: injected fault not drained "
                "after 2s (stuck step?); skipping the pool scramble",
                rep.name,
            )
            return
        try:
            eng.pool.k = eng.pool.k * 0.0 + 97.0
            eng.pool.v = eng.pool.v * 0.0 - 97.0
        except Exception:
            pass  # the fence is the fault; corruption is the drill's color

    def _probe_engine(self, eng) -> None:
        """One probe generation — a token through prefill AND decode —
        that must succeed before a replica (re)takes traffic. Raises on
        failure; shared by the restart worker, :meth:`probe_replica`,
        and the membership layer's admission/weight-swap gates."""
        probe_new = max(1, min(2, eng.max_seq_len - 1))
        probe = eng.submit(
            [1], probe_new, block=False, deadline=self.probe_timeout_s
        )
        if eng._thread is None:
            eng.run_until_idle()  # fleet not started: drive it inline
        probe.result(timeout=self.probe_timeout_s)

    def probe_replica(self, name: str) -> bool:
        """Run one probe generation against a replica WITHOUT touching
        its gate state — the health check the rolling weight swap runs
        on a drained member before re-admitting it. Returns whether the
        probe produced a token in time."""
        rep = self._replica(name)
        try:
            self._probe_engine(rep.engine)
            return True
        except Exception:
            logger.warning(
                "fleet: replica %s probe failed", rep.name, exc_info=True
            )
            return False

    def drain_replica(self, name: str) -> bool:
        """Administratively gate a replica out of NEW placements while
        its in-flight streams finish on it (the first step of a rolling
        restart / weight swap — a drain, not a fence: nothing fails).
        Session pins to the replica are dropped so affine traffic
        re-places immediately. Returns False unless the replica was
        active."""
        rep = self._replica(name)
        with rep.lock:
            if rep.state != "active":
                return False
            rep.state = "draining"
        with self._lock:
            victims = [
                s for s, (r, _) in self._sessions.items() if r is rep
            ]
            for s in victims:
                del self._sessions[s]
        _flight.record(
            "fleet", "drain", replica=rep.name, sessions_dropped=len(victims)
        )
        logger.warning(
            "fleet: replica %s draining (no new placements; %d session "
            "pin(s) dropped)",
            rep.name,
            len(victims),
        )
        self._wake.set()
        return True

    def admit_replica(self, name: str, probe: bool = True) -> bool:
        """Re-admit a drained or fenced replica to placement, gated on a
        probe generation by default (re-admitting a replica that cannot
        generate would just bounce traffic). The administrative twin of
        the restart worker's re-admission — it does NOT restart the
        engine first; callers that recycled the process or swapped
        weights already did. Returns whether the replica is active
        afterwards."""
        rep = self._replica(name)
        with rep.lock:
            if rep.state == "active":
                return True
            if rep.wedged or rep.restarting:
                return False
        if probe:
            try:
                self._probe_engine(rep.engine)
            except Exception:
                logger.warning(
                    "fleet: replica %s admission probe failed; it stays "
                    "%s",
                    rep.name,
                    rep.state,
                    exc_info=True,
                )
                return False
        with rep.lock:
            if rep.wedged or rep.restarting:
                return False  # fenced wedged while the probe ran
            rep.state = "active"
        _flight.record("fleet", "admit", replica=rep.name)
        logger.warning("fleet: replica %s re-admitted", rep.name)
        self._wake.set()
        return True

    def set_replica_tier(self, name: str, tier: str) -> None:
        """Re-role one replica at runtime (serve/tiers.py): the
        membership layer applies a joining member's advertised tier
        here, and an operator can re-shape a live fleet (e.g. grow the
        decode tier for a long-output workload) without restarts.
        In-flight streams are untouched — only FUTURE placements and
        handoffs see the new role."""
        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r}; expected one of {TIERS}")
        rep = self._replica(name)
        if rep.tier == tier:
            return
        old, rep.tier = rep.tier, tier
        _flight.record(
            "fleet", "retier", replica=rep.name, tier=tier, was=old
        )
        logger.info("fleet: replica %s re-roled %s -> %s", rep.name, old, tier)
        self._wake.set()

    def _add_replica(self, name: str, engine, tier: str = "mixed") -> None:
        """Grow the roster by one pre-built engine (a member joining the
        elastic fleet). Copy-on-write rebind: concurrent placement and
        watchdog sweeps keep iterating their snapshot."""
        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r}; expected one of {TIERS}")
        rep = _Replica(str(name), engine, tier=tier)
        self._install_pressure_hook(rep)
        with self._lock:
            if any(r.name == rep.name for r in self._replicas):
                raise ValueError(f"replica {rep.name!r} already exists")
            self._replicas = self._replicas + [rep]
        if self._thread is not None and engine._thread is None:
            try:
                engine.start()
            except Exception:
                logger.warning(
                    "fleet: replica %s failed to start on join",
                    rep.name,
                    exc_info=True,
                )
        _flight.record("fleet", "replica_join", replica=rep.name)
        self._wake.set()

    def _remove_replica(self, name: str) -> Optional[_Replica]:
        """Shrink the roster (a departed/fenced member leaving the
        elastic fleet). Session pins to the removed replica are dropped;
        the replica object is returned so the caller can drain or stop
        its engine. Unknown names return None (removal is idempotent —
        registry sweeps may race)."""
        with self._lock:
            rep = next(
                (r for r in self._replicas if r.name == name), None
            )
            if rep is None:
                return None
            self._replicas = [r for r in self._replicas if r is not rep]
            victims = [
                s for s, (r, _) in self._sessions.items() if r is rep
            ]
            for s in victims:
                del self._sessions[s]
        _flight.record("fleet", "replica_leave", replica=rep.name)
        self._wake.set()
        return rep

    def restart_replica(self, name: str) -> bool:
        """Manually restart + probe + re-admit a fenced replica (the
        ``auto_restart=False`` path). A no-op on an active replica
        (restarting one that is serving would preempt healthy traffic),
        on a wedged one (``restart()`` would block behind the stuck
        step — recycle the process), and while another restart worker
        already owns the replica. Returns whether the replica is active
        afterwards."""
        rep = self._replica(name)
        with rep.lock:
            if rep.state != "fenced" or rep.wedged or rep.restarting:
                return rep.state == "active"
            rep.restarting = True
        self._restart_worker(rep)
        return rep.state == "active"

    def _restart_worker(self, rep: _Replica) -> None:
        """Background recovery for one fenced replica: ``restart()``
        rebuilds device state (zero recompiles), then a probe generation
        must push one token through prefill AND decode before the
        replica takes traffic again — re-admitting a replica that
        cannot actually generate would just bounce the survivors."""
        try:
            eng = rep.engine
            # let the fence's injected fault drain the old traffic first:
            # restarting early would requeue survivors on THIS replica
            # instead of letting the router replay them, and the probe
            # would race the pending kill
            drained = time.monotonic() + 5.0
            while time.monotonic() < drained and (
                eng._poison is not None or eng.scheduler.has_work()
            ):
                if self._stop_evt.is_set():
                    return
                time.sleep(0.005)
            if self._stop_evt.is_set() or self._closed:
                # the fleet stopped while this worker waited: restarting
                # (healthy=True, probe compute) AFTER stop() returned
                # would resurrect a replica the caller believes is down
                return
            try:
                eng.restart()
            except RuntimeError:
                logger.warning(
                    "fleet: replica %s restart refused (wedged stop?); "
                    "leaving it fenced",
                    rep.name,
                )
                return
            self._probe_engine(eng)
            if self._stop_evt.is_set() or self._closed:
                return  # stopped mid-probe: stay fenced, stay quiet
            with rep.lock:
                rep.state = "active"
                rep.wedged = False
            _flight.record("fleet", "readmit", replica=rep.name)
            logger.warning(
                "fleet: replica %s re-admitted (restart + probe ok)",
                rep.name,
            )
        except Exception:
            logger.warning(
                "fleet: replica %s probe failed; it stays fenced for the "
                "next watchdog attempt",
                rep.name,
                exc_info=True,
            )
        finally:
            rep.restarting = False

    def _poll_replicas(self) -> None:
        healthy = 0
        for rep in self._replicas:
            if rep.state == "active":
                try:
                    _chaos.site("fleet.replica_fault")
                    _chaos.site("fleet.replica_fault." + rep.name)
                except Exception as e:
                    self._kill_replica(rep, e)
            h = rep.engine.health()
            if rep.state in ("active", "draining"):
                wedged = (
                    h["last_step_age_s"] > self.wedge_timeout_s
                    and (h["queue_depth"] > 0 or h["active_slots"] > 0)
                    and bool(h["stepping_thread_alive"])
                )
                if not h["healthy"] or wedged:
                    self._fence(
                        rep,
                        RuntimeError(
                            "replica health probe failed "
                            f"(healthy={h['healthy']}, "
                            f"last_step_age_s={h['last_step_age_s']})"
                        ),
                        wedged=wedged,
                    )
            if rep.state == "fenced" and not rep.wedged and self.auto_restart:
                with rep.lock:
                    # compare-and-set under the replica lock: a manual
                    # restart_replica() may own the replica already
                    spawn = rep.state == "fenced" and not rep.restarting
                    if spawn:
                        rep.restarting = True
                if spawn:
                    threading.Thread(
                        target=self._restart_worker, args=(rep,), daemon=True
                    ).start()
            if rep.state == "active":
                healthy += 1
            _m_rep_queue.set(float(h["queue_depth"]), replica=rep.name)
            _m_rep_pages.set(float(h["pages_in_use"]), replica=rep.name)
        _m_replicas_healthy.set(float(healthy))
        for tier in TIERS:
            _m_tier_replicas.set(
                float(
                    sum(
                        1
                        for rep in self._replicas
                        if rep.state == "active" and rep.tier == tier
                    )
                ),
                tier=tier,
            )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Fleet":
        """Start every replica's stepping thread plus the fleet's
        router/watchdog thread. A stopped fleet may start again."""
        if self._thread is not None:
            raise RuntimeError("fleet already started")
        self._closed = False
        self._stop_evt.clear()
        self._wake.clear()
        for rep in self._replicas:
            self._install_pressure_hook(rep)
            if rep.engine._thread is None:
                rep.engine.start()
        self._thread = threading.Thread(target=self._supervise, daemon=True)
        self._thread.start()
        # the SLO actuator's session re-placement hook (weakly held —
        # a stopped/collected fleet unregisters itself)
        _tenancy.register_fleet(self)
        return self

    def _supervise(self) -> None:
        """The router thread: fence/restart on health, resubmit the
        failover queue. Logs loudly if it ever dies — a silent watchdog
        death would turn the next replica fault back into an outage."""
        try:
            while not self._stop_evt.is_set():
                self._poll_replicas()
                for hook in list(self._tick_hooks):
                    try:
                        hook()
                    except Exception:
                        logger.warning(
                            "fleet: tick hook %r failed", hook, exc_info=True
                        )
                self._drain_migrations()
                self._drain_failovers()
                self._wake.wait(self.watchdog_interval_s)
                self._wake.clear()
        except BaseException:
            if not self._stop_evt.is_set():
                logger.error(
                    "fleet supervisor thread died; failover and "
                    "re-admission are OFFLINE until restart",
                    exc_info=True,
                )
            raise

    def stop(self) -> None:
        """Stop the router and every replica; any still-open fleet
        handle fails (never strands its consumer)."""
        with self._lock:
            # under the fleet lock so a concurrent submit either
            # registers BEFORE this flag (and gets drained below) or
            # observes it at registration and sheds
            self._closed = True
        _tenancy.register_fleet(None)
        self._stop_evt.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # a zombie router fencing/replaying next to a future
                # start()'s router would double every failover action —
                # keep the reference (start() refuses while it is set)
                # and let a retried stop() join again; _stop_evt stays
                # set, so the thread exits whenever it unblocks
                logger.warning(
                    "fleet: router thread did not stop within 10s "
                    "(blocked in a drain?); stop() again to retry — "
                    "start() is refused until it exits"
                )
            else:
                self._thread = None
        for rep in self._replicas:
            try:
                rep.engine.stop()
            except Exception:
                logger.warning(
                    "fleet: replica %s stop failed", rep.name, exc_info=True
                )
        with self._lock:
            recs = list(self._inflight.values())
            self._inflight.clear()
            self._pending.clear()
            self._handoff.clear()
            self._imports.clear()
        err = RuntimeError("fleet stopped with the request in flight")
        for rec in recs:
            rec.handle._finish(err)  # no-op on already-settled handles

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
