"""Paged KV cache: a static-shape page pool + per-sequence page tables.

The decode-memory problem continuous batching creates: requests arrive
and finish at different times with different lengths, but a compiled
decode step wants ONE static cache shape. A per-request contiguous cache
(what :func:`~tensorframes_tpu.models.transformer_generate` allocates)
either recompiles as shapes change or wastes ``max_len`` rows per slot.
The paged layout (Ragged Paged Attention / vLLM's PagedAttention, see
PAPERS.md) decouples the two lifetimes:

- **device**: one pool of ``num_pages`` fixed-size pages per layer,
  ``[n_layers, num_pages + 1, page_size, n_kv_heads, head_dim]`` — the
  shape never changes, so the decode step compiles exactly once. The
  extra row at index ``num_pages`` is the TRASH page: writes from
  inactive slots and prompt padding land there, keeping every program
  input in-bounds without per-slot branches.
- **host**: a free-list allocator and per-sequence page tables
  (:class:`SequencePages`). Sequences grow one page at a time; a
  finished sequence's pages return to the pool immediately, so HBM is
  bounded by LIVE tokens, not by slots × max_len.

Pages are REFCOUNTED: the page indirection means any number of page
tables may name the same physical page, which is what shared-prefix KV
caching rides on — :class:`PrefixCache` maps token prefixes to the
pages that already hold their k/v, so identical system prompts /
few-shot templates dedup to one physical copy and a new request's
prefill skips the shared span entirely. A page returns to the free list
only when its LAST reference drops. Shared pages are immutable by
construction (only COMPLETE prompt pages are ever registered, and
decode appends past them); a request diverging inside a cached page
gets a private copy-on-write clone (the engine copies the page row,
then overwrites from the divergence point).

Exhaustion raises
:class:`~tensorframes_tpu.utils.failures.PagePoolExhausted` — the
scheduler's cue to evict cache entries, then preempt-and-requeue,
never a crash.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import chaos as _chaos
from ..utils.failures import PagePoolExhausted
from . import tenancy as _tenancy

__all__ = [
    "PageGroup",
    "PagePool",
    "PrefixCache",
    "SequencePages",
    "pages_needed",
]


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages required to hold ``tokens`` positions."""
    return -(-int(tokens) // int(page_size))


class PagePool:
    """Fixed-size KV page pool: device arrays with a STATIC shape plus a
    host-side free-list allocator.

    ``k``/``v`` are ``[n_layers, num_pages + 1, page_size, n_kv_heads,
    head_dim]`` jax arrays — page ``num_pages`` is the trash page (see
    module docstring). The arrays are exposed as plain attributes because
    the engine's compiled step functions consume and return them
    functionally (donated on TPU); the pool only tracks WHICH pages are
    live, never their contents."""

    def __init__(
        self,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        num_pages: int,
        page_size: int,
        dtype=None,
        sharding=None,
    ):
        import jax.numpy as jnp

        if num_pages < 1 or page_size < 1:
            raise ValueError(
                f"need num_pages >= 1 and page_size >= 1; got "
                f"{num_pages}, {page_size}"
            )
        self.n_layers = int(n_layers)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        #: optional jax sharding pinning the KV-HEAD axis across a
        #: device mesh (tensor-parallel serving, ``serve/tp.py``): each
        #: chip holds its slice of every page, so one page costs
        #: 1/N of its solo bytes per chip and a fixed per-chip HBM
        #: budget holds N× the pages — the aggregate-capacity unlock.
        #: Page BOOKKEEPING (free list, refcounts, tables) is untouched:
        #: a page is still one logical unit spanning all shards.
        self.sharding = sharding
        #: index of the trash page (valid to write, never read unmasked)
        self.trash_page = self.num_pages
        shape = (
            self.n_layers,
            self.num_pages + 1,
            self.page_size,
            self.n_kv_heads,
            self.head_dim,
        )
        dtype = jnp.float32 if dtype is None else dtype
        self.k = self.place(jnp.zeros(shape, dtype))
        self.v = self.place(jnp.zeros(shape, dtype))
        #: named parallel page-array families addressed by the SAME page
        #: indices as ``k``/``v`` (:meth:`add_group`) — how a draft
        #: model's KV rides the pool without its own allocator: one
        #: logical page spans the main arrays AND every group's, so
        #: alloc/free/refcount/defragment stay single-sourced here
        self.groups: Dict[str, "PageGroup"] = {}
        self._lock = threading.Lock()
        # LIFO free list: recently-freed pages are reused first (their
        # contents are hottest in any cache hierarchy, and reuse keeps
        # the live set compact without explicit defragmentation). The
        # shadow set makes the double-free guard O(1) per page — free()
        # sits on the request-finish/preempt hot path.
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self._free_set = set(self._free)
        #: per-page reference count: 1 at alloc, +1 per ref() (a second
        #: page table or the prefix cache naming the same page), -1 per
        #: free(); the page returns to the free list at 0
        self._refcount = np.zeros(self.num_pages, np.int32)

    def place(self, arr):
        """Pin ``arr`` to the pool's sharding (identity when unsharded).
        Every eager rewrite of the pool arrays — :meth:`reset`,
        :meth:`defragment`, the engine's copy-on-write clone — runs
        through this so the compiled step programs always receive
        already-placed inputs instead of resharding on dispatch."""
        if self.sharding is None:
            return arr
        import jax

        return jax.device_put(arr, self.sharding)

    def add_group(
        self,
        name: str,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=None,
        sharding=None,
    ) -> "PageGroup":
        """Attach a named PARALLEL page-array family (``[n_layers,
        num_pages + 1, page_size, n_kv_heads, head_dim]``) addressed by
        the same page indices as the pool's own ``k``/``v`` — the
        speculative-decoding draft model's KV page group
        (docs/serving_llm.md "Speculative decoding"). Page BOOKKEEPING
        (free list, refcounts, tables) is untouched: a page is one
        logical unit spanning the main arrays and every group, so a
        sequence's single page list covers its target AND draft KV, and
        shared-prefix pages dedup both at once. :meth:`defragment`
        renumbers group contents with the same permutation;
        :meth:`reset` re-zeros them."""
        if name in self.groups:
            raise ValueError(f"page group {name!r} already exists")
        g = PageGroup(
            self, n_layers, n_kv_heads, head_dim,
            dtype=dtype, sharding=sharding,
        )
        self.groups[name] = g
        return g

    # -- allocation --------------------------------------------------------

    def alloc(self, n: int = 1) -> List[int]:
        """Take ``n`` pages off the free list — all or nothing (a partial
        grant would leak pages when the caller unwinds). Raises
        :class:`PagePoolExhausted` when fewer than ``n`` are free."""
        _chaos.site("kv_pages.alloc")
        with self._lock:
            if n > len(self._free):
                raise PagePoolExhausted(
                    f"KV page pool exhausted: need {n} page(s), "
                    f"{len(self._free)}/{self.num_pages} free"
                )
            grant = self._free[-n:][::-1]
            del self._free[len(self._free) - n :]
            self._free_set.difference_update(grant)
            self._refcount[grant] = 1
            return grant

    def ref(self, pages: Iterable[int]) -> None:
        """Take one more reference on each LIVE page — how a second page
        table (or the prefix cache) comes to share a physical page. The
        sharer releases through the same :meth:`free` as an owner."""
        with self._lock:
            pages = [int(p) for p in pages]
            for p in pages:
                if not 0 <= p < self.num_pages:
                    raise ValueError(f"page {p} is not a pool page")
                if p in self._free_set or self._refcount[p] < 1:
                    raise ValueError(f"cannot ref free page {p}")
            for p in pages:
                self._refcount[p] += 1

    def free(self, pages: Iterable[int]) -> int:
        """Drop one reference per page; pages whose LAST reference this
        was return to the free list. Returns how many actually freed
        (the prefix cache's eviction loop needs the distinction: evicting
        an entry whose pages live sequences still share frees nothing
        NOW — those pages free later, when the sequences release)."""
        freed = 0
        with self._lock:
            for p in pages:
                p = int(p)
                if not 0 <= p < self.num_pages:
                    raise ValueError(f"page {p} is not a pool page")
                if p in self._free_set or self._refcount[p] < 1:
                    raise ValueError(f"double free of page {p}")
                self._refcount[p] -= 1
                if self._refcount[p] == 0:
                    self._free.append(p)
                    self._free_set.add(p)
                    freed += 1
        return freed

    @property
    def pages_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - self.pages_free

    @property
    def pages_shared(self) -> int:
        """Pages currently named by more than one reference (sequences
        and/or the prefix cache) — the dedup the shared-prefix cache is
        buying, exported as the ``serve.kv_pages_shared`` gauge."""
        with self._lock:
            return int((self._refcount > 1).sum())

    def reset(self) -> None:
        """Crash recovery: discard ALL device state and bookkeeping —
        fresh zeroed page arrays, every page back on the free list. The
        caller (:meth:`GenerationEngine.restart`) must first requeue
        every live sequence (their KV contents are rebuilt from
        host-side progress by re-prefill); any :class:`SequencePages`
        still holding pages after this call is stale."""
        import jax.numpy as jnp

        with self._lock:
            shape = (
                self.n_layers,
                self.num_pages + 1,
                self.page_size,
                self.n_kv_heads,
                self.head_dim,
            )
            dtype = self.k.dtype
            self.k = self.place(jnp.zeros(shape, dtype))
            self.v = self.place(jnp.zeros(shape, dtype))
            for g in self.groups.values():
                g.reset()
            self._free = list(range(self.num_pages - 1, -1, -1))
            self._free_set = set(self._free)
            self._refcount[:] = 0

    # -- defragmentation ---------------------------------------------------

    def defragment(
        self,
        sequences: Sequence["SequencePages"],
        page_lists: Sequence[List[int]] = (),
    ) -> Dict[int, int]:
        """Compact every live page to the lowest pool indices: one device
        gather per pool array rewrites page CONTENTS, and each sequence's
        table is renumbered in place. Returns the ``old -> new`` remap.

        ``page_lists``: additional page-number lists to renumber in
        place — the prefix cache's entries pass theirs here, so cached
        prefixes survive compaction. A page named by several owners is
        legitimate exactly when its refcount covers them; anything past
        the refcount is the corruption this check existed to catch.

        With an indirection table any free page is as good as any other,
        so steady-state serving never needs this; it exists for pool
        RESIZE (shrink to the live prefix, then slice the arrays) and for
        snapshot/restore, where a contiguous live region is the useful
        invariant."""
        with self._lock:
            owners: Dict[int, int] = {}
            all_lists: List[List[int]] = [seq.pages for seq in sequences]
            all_lists.extend(page_lists)
            for pages in all_lists:
                for p in pages:
                    owners[p] = owners.get(p, 0) + 1
            for p, n in owners.items():
                if n > int(self._refcount[p]):
                    raise ValueError(
                        f"page {p} named by {n} owners but refcount is "
                        f"{int(self._refcount[p])}"
                    )
            remap = {old: new for new, old in enumerate(sorted(owners))}
            # perm[new] = old for live pages; free pages fill the tail in
            # index order; trash stays trash
            tail = [p for p in range(self.num_pages) if p not in remap]
            perm = np.empty(self.num_pages + 1, np.int32)
            for old, new in remap.items():
                perm[new] = old
            perm[len(remap) : self.num_pages] = tail
            perm[self.num_pages] = self.trash_page
            self.k = self.place(self.k[:, perm])
            self.v = self.place(self.v[:, perm])
            for g in self.groups.values():
                # a page is one logical unit across every group: the
                # draft KV rows move with the same permutation, so page
                # lists stay valid for both models
                g.k = g.place(g.k[:, perm])
                g.v = g.place(g.v[:, perm])
            self._refcount = self._refcount[perm[: self.num_pages]]
            for pages in all_lists:
                pages[:] = [remap[p] for p in pages]
            self._free = list(range(self.num_pages - 1, len(remap) - 1, -1))
            self._free_set = set(self._free)
            return remap

    def __repr__(self) -> str:
        return (
            f"PagePool(pages={self.num_pages}, page_size={self.page_size}, "
            f"in_use={self.pages_in_use})"
        )


class PageGroup:
    """One named parallel page-array family over a :class:`PagePool`'s
    index space (:meth:`PagePool.add_group`): its own ``k``/``v`` device
    arrays with the pool's ``num_pages + 1`` / ``page_size`` geometry
    (trash row included) but its own layer/head/dim shape and dtype —
    the speculative-decoding DRAFT model's KV. No allocator of its own:
    page index ``p`` in a sequence's table names row ``p`` here exactly
    as it does in the main arrays."""

    def __init__(
        self,
        pool: "PagePool",
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=None,
        sharding=None,
    ):
        import jax.numpy as jnp

        self.pool = pool
        self.n_layers = int(n_layers)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.sharding = sharding
        self._dtype = pool.k.dtype if dtype is None else dtype
        self.k = self.place(jnp.zeros(self._shape(), self._dtype))
        self.v = self.place(jnp.zeros(self._shape(), self._dtype))

    def _shape(self):
        return (
            self.n_layers,
            self.pool.num_pages + 1,
            self.pool.page_size,
            self.n_kv_heads,
            self.head_dim,
        )

    def place(self, arr):
        """Pin ``arr`` to this group's own sharding (identity when
        unsharded — the draft group stays replicated even under a
        tensor-parallel pool)."""
        if self.sharding is None:
            return arr
        import jax

        return jax.device_put(arr, self.sharding)

    def reset(self) -> None:
        """Fresh zeroed arrays (crash recovery, with
        :meth:`PagePool.reset`)."""
        import jax.numpy as jnp

        self.k = self.place(jnp.zeros(self._shape(), self._dtype))
        self.v = self.place(jnp.zeros(self._shape(), self._dtype))


class SequencePages:
    """One sequence's slice of the pool: the ordered page list (page ``i``
    holds positions ``i*page_size .. (i+1)*page_size - 1``) and growth /
    release bookkeeping. Pure host state — the device-visible form is
    :meth:`table`."""

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.pages: List[int] = []

    @property
    def capacity(self) -> int:
        """Token positions the currently-held pages can store."""
        return len(self.pages) * self.pool.page_size

    def ensure(self, tokens: int) -> None:
        """Grow the page list until ``tokens`` positions fit. All-or-
        nothing per call; raises :class:`PagePoolExhausted` (holdings
        unchanged) when the pool cannot supply the missing pages."""
        missing = pages_needed(tokens, self.pool.page_size) - len(self.pages)
        if missing > 0:
            self.pages.extend(self.pool.alloc(missing))

    def release(self) -> None:
        """Return every held page to the pool (idempotent)."""
        if self.pages:
            self.pool.free(self.pages)
            self.pages = []

    def table(self, max_pages: int) -> np.ndarray:
        """The ``[max_pages]`` int32 page table the compiled step reads —
        held pages in position order, trash-filled past the end (those
        entries are masked by the position mask, but must stay in
        bounds)."""
        if len(self.pages) > max_pages:
            raise ValueError(
                f"sequence holds {len(self.pages)} pages > max_pages "
                f"{max_pages}"
            )
        out = np.full(max_pages, self.pool.trash_page, np.int32)
        out[: len(self.pages)] = self.pages
        return out


class _PrefixEntry:
    """One cached prompt prefix: the page-aligned token span and the
    physical pages holding its k/v (the cache holds one reference on
    each). ``keys`` are the per-page-count digests registered in the
    lookup index, kept so eviction can remove exactly its own keys."""

    __slots__ = ("tokens", "pages", "keys", "full_key", "priority")

    def __init__(
        self, tokens: np.ndarray, pages: List[int], priority: int = 1
    ):
        self.tokens = tokens
        self.pages = pages
        self.keys: List[bytes] = []
        self.full_key: bytes = b""
        #: highest tenant-priority rank that registered this prefix
        #: (``serve/tenancy.py``): priority-weighted eviction drops
        #: low-rank entries first when the QoS plane is on
        self.priority = int(priority)


class PrefixCache:
    """Token-prefix -> physical-pages index over a :class:`PagePool` —
    shared-prefix KV caching (vLLM's automatic prefix caching shaped for
    the static-pool engine).

    A finished prefill registers its prompt's COMPLETE pages
    (:meth:`insert`); admission asks :meth:`acquire` for the longest
    page-aligned cached prefix of a new prompt and gets those pages
    refcounted into the new sequence's table, so the engine prefills
    only the uncached suffix (chunked prefill picks up mid-prompt).
    Shared pages are immutable: decode appends strictly past a prompt's
    complete pages, so divergence never writes into one. A prompt that
    diverges INSIDE a cached page gets a private copy-on-write clone:
    :meth:`acquire` returns the donor page to copy plus how many of its
    leading positions are reusable; the engine copies the page row and
    overwrites from the divergence point.

    Keys are sha1 digests of the token bytes per page-aligned prefix
    length, verified against the stored tokens on hit (digest collision
    can downgrade a hit to a miss, never corrupt). Entries are LRU:
    bounded by ``max_entries``, and evicted on demand when the pool runs
    dry (:meth:`evict_pages` — the scheduler tries that before
    preempting live sequences). Thread-safety: a lock guards the maps —
    mutation happens on the engine's stepping thread, but stats and
    ``/healthz`` read concurrently."""

    def __init__(self, pool: PagePool, max_entries: int = 256):
        self.pool = pool
        self.page_size = pool.page_size
        self.max_entries = int(max_entries)
        self._entries: "OrderedDict[bytes, _PrefixEntry]" = OrderedDict()
        self._index: Dict[bytes, _PrefixEntry] = {}
        self._lock = threading.Lock()
        #: host-side stats (obs counters live in the engine): acquire
        #: calls, acquires that returned any cached tokens, and tokens
        #: whose prefill was skipped thanks to the cache
        self.lookups = 0
        self.hits = 0
        self.tokens_saved = 0

    @staticmethod
    def _key(tokens: np.ndarray) -> bytes:
        return hashlib.sha1(
            np.ascontiguousarray(tokens, np.int32).tobytes()
        ).digest()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "lookups": self.lookups,
                "hits": self.hits,
                "tokens_saved": self.tokens_saved,
            }

    # -- registration ------------------------------------------------------

    def insert(
        self,
        prompt: np.ndarray,
        pages: Sequence[int],
        priority: int = 1,
    ) -> bool:
        """Register a prefilled prompt's COMPLETE pages (``len(prompt) //
        page_size`` of them — a partial trailing page is still mutable
        and never shared). Takes one pool reference per page; idempotent
        for an already-registered prompt (LRU touch only, and the entry
        keeps the HIGHEST priority any registrant gave it — a prefix an
        interactive tenant shares must not evict on a batch tenant's
        rank). Returns whether a new entry was created."""
        prompt = np.asarray(prompt, np.int32).ravel()
        k_full = len(prompt) // self.page_size
        if k_full < 1:
            return False
        tokens = prompt[: k_full * self.page_size].copy()
        full_key = self._key(tokens)
        with self._lock:
            if full_key in self._entries:
                ent = self._entries[full_key]
                ent.priority = max(ent.priority, int(priority))
                self._entries.move_to_end(full_key)
                return False
            ent = _PrefixEntry(
                tokens, [int(p) for p in pages[:k_full]], priority
            )
            self.pool.ref(ent.pages)
            ent.full_key = full_key
            for k in range(1, k_full + 1):
                key = self._key(tokens[: k * self.page_size])
                # longest-prefix lookups walk k downward, so pointing a
                # shorter shared prefix at the newest entry is safe even
                # when it displaces an older entry's short keys
                self._index[key] = ent
                ent.keys.append(key)
            self._entries[full_key] = ent
            while len(self._entries) > self.max_entries:
                self._drop_locked(next(iter(self._entries)))
            return True

    # -- lookup ------------------------------------------------------------

    def acquire(
        self, prompt: np.ndarray
    ) -> Tuple[List[int], Optional[int], int]:
        """Longest cached page-aligned prefix of ``prompt``; returns
        ``(shared_pages, cow_src_page, cached_tokens)``.

        ``shared_pages`` arrive with one NEW reference each (the caller
        owns it; release through the usual ``free``). ``cow_src_page``,
        when set, also carries one TEMPORARY reference: the prompt
        diverges (or simply ends) inside the donor's next page, and its
        first ``cached_tokens - len(shared_pages) * page_size``
        positions are reusable once the caller clones the page — the
        caller must ``pool.free([cow_src_page])`` after cloning (the
        reference pins the donor contents until then).

        ``cached_tokens`` is capped at ``len(prompt) - 1``: the last
        prompt position must always be recomputed, because the first
        sampled token needs its logits."""
        prompt = np.asarray(prompt, np.int32).ravel()
        ps = self.page_size
        with self._lock:
            self.lookups += 1
            kcap = (len(prompt) - 1) // ps
            for k in range(kcap, 0, -1):
                ent = self._index.get(self._key(prompt[: k * ps]))
                if ent is None:
                    continue
                if not np.array_equal(ent.tokens[: k * ps], prompt[: k * ps]):
                    continue  # digest collision: treat as a miss
                cached = k * ps
                cow_src: Optional[int] = None
                if len(ent.pages) > k:
                    # partial-page extension: count matching tokens into
                    # the donor's next page, capped to plen - 1
                    upto = min(len(prompt) - 1, (k + 1) * ps) - k * ps
                    nxt = ent.tokens[k * ps : k * ps + upto]
                    m = int(
                        np.argmin(
                            np.concatenate(
                                [
                                    nxt == prompt[k * ps : k * ps + upto],
                                    [False],
                                ]
                            )
                        )
                    )
                    if m > 0:
                        cow_src = ent.pages[k]
                        cached += m
                shared = list(ent.pages[:k])
                self.pool.ref(shared)
                if cow_src is not None:
                    self.pool.ref([cow_src])
                self._entries.move_to_end(ent.full_key)
                self.hits += 1
                self.tokens_saved += cached
                return shared, cow_src, cached
            return [], None, 0

    # -- eviction ----------------------------------------------------------

    def _drop_locked(self, full_key: bytes) -> int:
        ent = self._entries.pop(full_key)
        for key in ent.keys:
            if self._index.get(key) is ent:
                del self._index[key]
        return self.pool.free(ent.pages)

    def evict_pages(self, need: int) -> int:
        """Drop least-recently-used entries until at least ``need`` pages
        returned to the free list, or the cache is empty. Returns pages
        actually freed — entries whose pages live sequences still share
        free nothing NOW (the sequence's release frees them later), so a
        0 return with entries remaining is possible and the caller
        should fall through to preemption."""
        freed = 0
        with self._lock:
            if _tenancy.enabled():
                # priority-weighted: low-rank tenants' prefixes pay
                # first; the sort is stable over insertion order, so
                # WITHIN a rank eviction stays exactly LRU. QoS off
                # takes the plain-LRU loop below, byte-identical to
                # the pre-tenancy cache.
                order = sorted(
                    self._entries.values(),
                    key=lambda ent: ent.priority,
                )
                for ent in order:
                    if freed >= need:
                        break
                    freed += self._drop_locked(ent.full_key)
                return freed
            while freed < need and self._entries:
                freed += self._drop_locked(next(iter(self._entries)))
        return freed

    def clear(self, free_pages: bool = True) -> None:
        """Drop every entry. ``free_pages=False`` skips the pool
        release — for use right AFTER :meth:`PagePool.reset`, which
        already rebuilt the free list (freeing then would corrupt it)."""
        with self._lock:
            if free_pages:
                while self._entries:
                    self._drop_locked(next(iter(self._entries)))
            else:
                self._entries.clear()
                self._index.clear()

    def entry_page_lists(self) -> List[List[int]]:
        """The live entries' page lists, for
        :meth:`PagePool.defragment`'s in-place renumbering."""
        with self._lock:
            return [ent.pages for ent in self._entries.values()]
