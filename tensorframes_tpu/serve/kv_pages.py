"""Paged KV cache: a static-shape page pool + per-sequence page tables.

The decode-memory problem continuous batching creates: requests arrive
and finish at different times with different lengths, but a compiled
decode step wants ONE static cache shape. A per-request contiguous cache
(what :func:`~tensorframes_tpu.models.transformer_generate` allocates)
either recompiles as shapes change or wastes ``max_len`` rows per slot.
The paged layout (Ragged Paged Attention / vLLM's PagedAttention, see
PAPERS.md) decouples the two lifetimes:

- **device**: one pool of ``num_pages`` fixed-size pages per layer,
  ``[n_layers, num_pages + 1, page_size, n_kv_heads, head_dim]`` — the
  shape never changes, so the decode step compiles exactly once. The
  extra row at index ``num_pages`` is the TRASH page: writes from
  inactive slots and prompt padding land there, keeping every program
  input in-bounds without per-slot branches.
- **host**: a free-list allocator and per-sequence page tables
  (:class:`SequencePages`). Sequences grow one page at a time; a
  finished sequence's pages return to the pool immediately, so HBM is
  bounded by LIVE tokens, not by slots × max_len.

Exhaustion raises
:class:`~tensorframes_tpu.utils.failures.PagePoolExhausted` — the
scheduler's cue to preempt-and-requeue, never a crash.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Sequence

import numpy as np

from ..utils import chaos as _chaos
from ..utils.failures import PagePoolExhausted

__all__ = ["PagePool", "SequencePages", "pages_needed"]


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages required to hold ``tokens`` positions."""
    return -(-int(tokens) // int(page_size))


class PagePool:
    """Fixed-size KV page pool: device arrays with a STATIC shape plus a
    host-side free-list allocator.

    ``k``/``v`` are ``[n_layers, num_pages + 1, page_size, n_kv_heads,
    head_dim]`` jax arrays — page ``num_pages`` is the trash page (see
    module docstring). The arrays are exposed as plain attributes because
    the engine's compiled step functions consume and return them
    functionally (donated on TPU); the pool only tracks WHICH pages are
    live, never their contents."""

    def __init__(
        self,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        num_pages: int,
        page_size: int,
        dtype=None,
    ):
        import jax.numpy as jnp

        if num_pages < 1 or page_size < 1:
            raise ValueError(
                f"need num_pages >= 1 and page_size >= 1; got "
                f"{num_pages}, {page_size}"
            )
        self.n_layers = int(n_layers)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        #: index of the trash page (valid to write, never read unmasked)
        self.trash_page = self.num_pages
        shape = (
            self.n_layers,
            self.num_pages + 1,
            self.page_size,
            self.n_kv_heads,
            self.head_dim,
        )
        dtype = jnp.float32 if dtype is None else dtype
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self._lock = threading.Lock()
        # LIFO free list: recently-freed pages are reused first (their
        # contents are hottest in any cache hierarchy, and reuse keeps
        # the live set compact without explicit defragmentation). The
        # shadow set makes the double-free guard O(1) per page — free()
        # sits on the request-finish/preempt hot path.
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self._free_set = set(self._free)

    # -- allocation --------------------------------------------------------

    def alloc(self, n: int = 1) -> List[int]:
        """Take ``n`` pages off the free list — all or nothing (a partial
        grant would leak pages when the caller unwinds). Raises
        :class:`PagePoolExhausted` when fewer than ``n`` are free."""
        _chaos.site("kv_pages.alloc")
        with self._lock:
            if n > len(self._free):
                raise PagePoolExhausted(
                    f"KV page pool exhausted: need {n} page(s), "
                    f"{len(self._free)}/{self.num_pages} free"
                )
            grant = self._free[-n:][::-1]
            del self._free[len(self._free) - n :]
            self._free_set.difference_update(grant)
            return grant

    def free(self, pages: Iterable[int]) -> None:
        with self._lock:
            for p in pages:
                p = int(p)
                if not 0 <= p < self.num_pages:
                    raise ValueError(f"page {p} is not a pool page")
                if p in self._free_set:
                    raise ValueError(f"double free of page {p}")
                self._free.append(p)
                self._free_set.add(p)

    @property
    def pages_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - self.pages_free

    def reset(self) -> None:
        """Crash recovery: discard ALL device state and bookkeeping —
        fresh zeroed page arrays, every page back on the free list. The
        caller (:meth:`GenerationEngine.restart`) must first requeue
        every live sequence (their KV contents are rebuilt from
        host-side progress by re-prefill); any :class:`SequencePages`
        still holding pages after this call is stale."""
        import jax.numpy as jnp

        with self._lock:
            shape = (
                self.n_layers,
                self.num_pages + 1,
                self.page_size,
                self.n_kv_heads,
                self.head_dim,
            )
            dtype = self.k.dtype
            self.k = jnp.zeros(shape, dtype)
            self.v = jnp.zeros(shape, dtype)
            self._free = list(range(self.num_pages - 1, -1, -1))
            self._free_set = set(self._free)

    # -- defragmentation ---------------------------------------------------

    def defragment(
        self, sequences: Sequence["SequencePages"]
    ) -> Dict[int, int]:
        """Compact every live page to the lowest pool indices: one device
        gather per pool array rewrites page CONTENTS, and each sequence's
        table is renumbered in place. Returns the ``old -> new`` remap.

        With an indirection table any free page is as good as any other,
        so steady-state serving never needs this; it exists for pool
        RESIZE (shrink to the live prefix, then slice the arrays) and for
        snapshot/restore, where a contiguous live region is the useful
        invariant."""
        with self._lock:
            live: List[int] = []
            for seq in sequences:
                live.extend(seq.pages)
            if len(set(live)) != len(live):
                raise ValueError("a page is owned by two sequences")
            remap = {old: new for new, old in enumerate(sorted(live))}
            # perm[new] = old for live pages; free pages fill the tail in
            # index order; trash stays trash
            tail = [p for p in range(self.num_pages) if p not in remap]
            perm = np.empty(self.num_pages + 1, np.int32)
            for old, new in remap.items():
                perm[new] = old
            perm[len(remap) : self.num_pages] = tail
            perm[self.num_pages] = self.trash_page
            self.k = self.k[:, perm]
            self.v = self.v[:, perm]
            for seq in sequences:
                seq.pages = [remap[p] for p in seq.pages]
            self._free = list(range(self.num_pages - 1, len(remap) - 1, -1))
            self._free_set = set(self._free)
            return remap

    def __repr__(self) -> str:
        return (
            f"PagePool(pages={self.num_pages}, page_size={self.page_size}, "
            f"in_use={self.pages_in_use})"
        )


class SequencePages:
    """One sequence's slice of the pool: the ordered page list (page ``i``
    holds positions ``i*page_size .. (i+1)*page_size - 1``) and growth /
    release bookkeeping. Pure host state — the device-visible form is
    :meth:`table`."""

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.pages: List[int] = []

    @property
    def capacity(self) -> int:
        """Token positions the currently-held pages can store."""
        return len(self.pages) * self.pool.page_size

    def ensure(self, tokens: int) -> None:
        """Grow the page list until ``tokens`` positions fit. All-or-
        nothing per call; raises :class:`PagePoolExhausted` (holdings
        unchanged) when the pool cannot supply the missing pages."""
        missing = pages_needed(tokens, self.pool.page_size) - len(self.pages)
        if missing > 0:
            self.pages.extend(self.pool.alloc(missing))

    def release(self) -> None:
        """Return every held page to the pool (idempotent)."""
        if self.pages:
            self.pool.free(self.pages)
            self.pages = []

    def table(self, max_pages: int) -> np.ndarray:
        """The ``[max_pages]`` int32 page table the compiled step reads —
        held pages in position order, trash-filled past the end (those
        entries are masked by the position mask, but must stay in
        bounds)."""
        if len(self.pages) > max_pages:
            raise ValueError(
                f"sequence holds {len(self.pages)} pages > max_pages "
                f"{max_pages}"
            )
        out = np.full(max_pages, self.pool.trash_page, np.int32)
        out[: len(self.pages)] = self.pages
        return out
