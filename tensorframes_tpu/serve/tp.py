"""Tensor-parallel step programs: one serving replica spans N chips.

The solo :class:`~.engine.GenerationEngine` compiles three step programs
(prefill ``[1, max_seq_len]``, prefill-chunk ``[1, C]``, decode
``[max_slots]``) for one chip — plus the speculative VERIFY program
(``[max_slots, k + 1]``) when a draft model is attached. This module
builds the SAME programs as ``jit(shard_map(...))`` over a 1-D device
mesh (ROADMAP item 1a), so one replica's model weights and KV pool span
``N`` chips while keeping every contract solo serving established (the
DRAFT program is deliberately not here: it runs replicated — its
proposals steer how many positions verify covers, never their values):

- **byte-identical decode streams at every TP degree** — greedy AND
  seeded. Float matmuls are not associative, so any plan that changes a
  reduction's shape (Megatron row-parallel partial sums, column-sliced
  GEMMs) can flip a late-decode argmax and break the contract. The plan
  here shards only what is bit-exact by construction:

  * the **KV page pool and the per-head attention walk** shard along
    the KV-HEAD axis. The head axis is a pure batch axis in every
    attention contraction (scores reduce over ``head_dim``, the
    weighted sum over positions, both per head), so each shard's local
    heads compute bit-for-bit what the solo program computes for those
    heads, and the tiled all-gather of per-head context reassembles the
    solo activation exactly;
  * **weights shard AT REST** (``transformer_tp_specs``: qkv/up on
    output columns, proj/down on their hidden rows) and are
    **all-gathered to full inside the step** (``gather_tp_params``) —
    a tiled gather reconstructs the solo weight matrix bit-for-bit, so
    every dense matmul runs at the solo program's exact shape on exact
    inputs. Logits are computed replicated off the (replicated, tied)
    embedding; sampling runs on those replicated logits, identical on
    every shard.

  The trade: per-chip WEIGHT and KV memory scale ~1/N (the
  model-bigger-than-one-chip unlock) and the decode-dominant paged
  read's bandwidth and FLOPs scale 1/N, while dense projections are
  computed replicated (decode batches are tiny — the paged read is the
  steady-state ceiling) at the cost of per-step weight gathers, the
  FSDP-style bytes-for-determinism trade this contract forces.

- **≤ 3 compiled step programs per replica** at any TP degree: the
  mesh is static program structure, shapes are unchanged, and jit keys
  on the same abstract signatures the solo programs key on.

- **aggregate KV capacity scales with N**: each page spans the shards
  (1/N bytes per chip), so the engine sizes the pool at
  ``num_pages × N`` total pages for the same per-chip budget —
  ``serve.pages_capacity`` reports the scaled total, and a workload
  that exhausts TP=1 admission serves preemption-free at TP=2.

Tests drive TP=2/4 on the CPU-simulated mesh
(``xla_force_host_platform_device_count``, the conftest default), so
tier-1 exercises the whole plan without hardware; on real chips the
collectives ride ICI exactly like the ``parallel/`` primitives
(MULTICHIP_r0*.json measured the rings these gathers lower to).
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np

from ..models.transformer import (
    filter_logits,
    gather_tp_params,
    transformer_prefill_chunk,
    transformer_step,
    transformer_verify_chunk,
)

__all__ = [
    "estimate_collective_seconds",
    "tp_decode_impl",
    "tp_kv_specs",
    "tp_prefill_chunk_impl",
    "tp_prefill_impl",
    "tp_verify_impl",
    "validate_tp_mesh",
]


def validate_tp_mesh(mesh, n_heads: int, n_kv: int, d_ff: int) -> str:
    """Reject meshes the plan cannot shard evenly; returns the mesh's
    (single) axis name. Head counts must divide so the KV-head slicing
    lands on whole heads; ``d_ff`` must divide so the at-rest weight
    shards are even (``shard_map`` requires even shards)."""
    axes = tuple(mesh.axis_names)
    if len(axes) != 1:
        raise ValueError(
            f"serving meshes are 1-D (one tensor-parallel axis); got "
            f"axes {axes} — compose dp by running one replica per mesh "
            f"(the fleet), not inside one engine"
        )
    tp = int(mesh.devices.size)
    for what, val in (
        ("n_kv_heads", n_kv),
        ("n_heads", n_heads),
        ("d_ff", d_ff),
    ):
        if val % tp:
            raise ValueError(
                f"{what} ({val}) must divide by the mesh size ({tp}): "
                f"the KV pool and weight shards split evenly or not at "
                f"all"
            )
    from ..parallel.compat import has_shard_map

    if not has_shard_map():
        import jax

        raise RuntimeError(
            f"jax {jax.__version__} offers no shard_map API; "
            f"tensor-parallel serving cannot build its step programs"
        )
    return axes[0]


def tp_kv_specs(axis: str):
    """(in/out) PartitionSpec for the pool's ``[L, pages, ps, n_kv,
    hd]`` arrays: sharded on the KV-head axis."""
    from jax.sharding import PartitionSpec as P

    return P(None, None, None, axis, None)


def _local_heads(arr, axis: str, kloc: int, head_axis: int):
    """This shard's contiguous KV-head slice of a full-head tensor."""
    import jax

    ti = jax.lax.axis_index(axis)
    return jax.lax.dynamic_slice_in_dim(
        arr, ti * kloc, kloc, axis=head_axis
    )


def _wrap(body, mesh, axis: str, param_specs, n_scalars: int):
    """jit-ready shard_map over one step body: params tree sharded per
    ``param_specs``, the two pool arrays on the KV-head axis, every
    other input replicated, outputs ``(k_pool, v_pool, tokens)``."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map

    kv = tp_kv_specs(axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, kv, kv) + (P(),) * n_scalars,
        out_specs=(kv, kv, P()),
        # replicated outputs (the sampled tokens) come from replicated
        # logits by construction; the static checker cannot infer that
        # through the gathers, so it is disabled exactly like the ring
        # and ulysses programs disable it
        check_vma=False,
    )


def tp_prefill_impl(engine, mesh, axis: str, n_heads: int, moe_top_k: int):
    """The TP prefill ``[1, max_seq_len]`` body with the ATTENTION
    sharded along KV heads (ROADMAP 1 follow-on — it used to compute
    full heads replicated, sharding only the KV scatter): the prompt
    runs the delegated chunk walk at positions ``0 .. P-1``, and each
    shard computes the dense causal attention for ITS head slice only —
    the head axis is a pure batch axis in both einsums, so every local
    head's scores/softmax/weighted-sum are bit-for-bit the solo
    program's for that head, and the tiled all-gather reassembles the
    solo context exactly. Per-chip prefill attention FLOPs and the
    ``O(P^2)`` score matrix both scale ~1/N. The shard's own k/v slice
    scatters straight into its pool shard (no full-head tensor is ever
    materialized), and sampling mirrors
    :meth:`GenerationEngine._prefill_impl` exactly."""
    import jax
    import jax.numpy as jnp

    from ..ops.attention import _NEG_BIG

    ps = engine.page_size
    trash = engine.pool.trash_page
    top_k = engine.top_k
    tp = int(mesh.devices.size)
    kloc = engine.pool.n_kv_heads // tp

    def prefill(p_loc, kp, vp, prompt, length, ptab, temp, seed, top_p):
        full = {**gather_tp_params(p_loc, axis), "n_heads": n_heads}
        plen = prompt.shape[1]
        pos = jnp.arange(plen)
        state = [kp, vp]

        def attend(li, q, k, v):
            # local heads only: q [1, P, n_kv, g, hd] -> [P, kloc, g,
            # hd]; k/v [1, P, n_kv, hd] -> [P, kloc, hd]
            ql = _local_heads(q[0], axis, kloc, 1)
            kl = _local_heads(k[0], axis, kloc, 1)
            vl = _local_heads(v[0], axis, kloc, 1)
            page = jnp.where(pos < length, ptab[pos // ps], trash)
            off = pos % ps
            state[0] = state[0].at[li, page, off].set(kl)
            state[1] = state[1].at[li, page, off].set(vl)
            hd = kl.shape[2]
            scale = 1.0 / float(np.sqrt(hd))
            # dense causal attention WITHIN the prompt, local heads:
            # the same einsum family as transformer_prefill's, minus
            # its batch axis — per head, bit-exact
            s = jnp.einsum("qkgd,tkd->kgqt", ql, kl) * scale
            causal = pos[:, None] >= pos[None, :]
            s = jnp.where(causal[None, None], s, _NEG_BIG)
            att = jnp.einsum(
                "kgqt,tkd->kgqd", jax.nn.softmax(s, axis=-1), vl
            )
            att = jax.lax.all_gather(att, axis, axis=0, tiled=True)
            # [n_kv, g, P, hd] -> [1, P, n_kv * g * hd]
            return att.transpose(2, 0, 1, 3).reshape(1, plen, -1)

        logits = transformer_prefill_chunk(
            full, prompt, pos, attend, moe_top_k=moe_top_k
        )
        last = logits[0, length - 1]
        greedy = jnp.argmax(last, axis=-1)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), length - 1)
        scaled = last[None] / jnp.maximum(
            jnp.asarray(temp, jnp.float32), 1e-6
        )
        filt = filter_logits(scaled, top_k=top_k, top_p=top_p)
        sampled = jax.random.categorical(key, filt, axis=-1)[0]
        tok = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)
        return state[0], state[1], tok

    return _wrap(prefill, mesh, axis, engine._tp_param_specs, 6)


def tp_prefill_chunk_impl(
    engine, mesh, axis: str, n_heads: int, moe_top_k: int
):
    """The TP ``[1, C]`` chunk body: per-head chunk attention on the
    local pool shard (scatter local k/v, gather local pages, the SAME
    einsum/mask family as the solo chunk program), context all-gathered
    back to full heads before the replicated residual walk."""
    import jax
    import jax.numpy as jnp

    from ..ops.attention import _NEG_BIG

    ps = engine.page_size
    trash = engine.pool.trash_page
    top_k = engine.top_k
    mp = engine._max_pages
    max_len = engine.max_seq_len
    tp = int(mesh.devices.size)
    kloc = engine.pool.n_kv_heads // tp

    def chunk_step(
        p_loc, kp, vp, chunk, start, valid, total_len, ptab, temp, seed,
        top_p,
    ):
        full = {**gather_tp_params(p_loc, axis), "n_heads": n_heads}
        c = chunk.shape[1]
        offs = jnp.arange(c)
        pos = start + offs
        pos_clipped = jnp.minimum(pos, max_len - 1)
        state = [kp, vp]

        def attend(li, q, k, v):
            # local heads only: q [1, C, n_kv, g, hd] -> [C, kloc, g,
            # hd]; k/v [1, C, n_kv, hd] -> [C, kloc, hd]
            ql = _local_heads(q[0], axis, kloc, 1)
            kl = _local_heads(k[0], axis, kloc, 1)
            vl = _local_heads(v[0], axis, kloc, 1)
            page = jnp.where(offs < valid, ptab[pos_clipped // ps], trash)
            off = pos_clipped % ps
            state[0] = state[0].at[li, page, off].set(kl)
            state[1] = state[1].at[li, page, off].set(vl)
            hd = kl.shape[2]
            t = mp * ps
            kg = state[0][li][ptab].reshape(t, kloc, hd)
            vg = state[1][li][ptab].reshape(t, kloc, hd)
            scale = 1.0 / float(np.sqrt(hd))
            s = jnp.einsum("ckgd,tkd->ckgt", ql, kg) * scale
            visible = jnp.arange(t)[None, :] <= pos[:, None]
            s = jnp.where(visible[:, None, None, :], s, _NEG_BIG)
            att = jnp.einsum(
                "ckgt,tkd->ckgd", jax.nn.softmax(s, axis=-1), vg
            )
            att = jax.lax.all_gather(att, axis, axis=1, tiled=True)
            return att.reshape(1, c, att.shape[1] * q.shape[3] * hd)

        logits = transformer_prefill_chunk(
            full, chunk, pos_clipped, attend, moe_top_k=moe_top_k
        )
        last = logits[0, valid - 1]
        greedy = jnp.argmax(last, axis=-1)
        key = jax.random.fold_in(
            jax.random.PRNGKey(seed), total_len - 1
        )
        scaled = last[None] / jnp.maximum(
            jnp.asarray(temp, jnp.float32), 1e-6
        )
        filt = filter_logits(scaled, top_k=top_k, top_p=top_p)
        sampled = jax.random.categorical(key, filt, axis=-1)[0]
        tok = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)
        return state[0], state[1], tok

    return _wrap(chunk_step, mesh, axis, engine._tp_param_specs, 8)


def tp_verify_impl(engine, mesh, axis: str, n_heads: int, moe_top_k: int):
    """The TP VERIFY ``[max_slots, k + 1]`` body — speculative
    decoding's batched multi-token check, sharded on KV heads exactly
    like decode: each shard scatters its head slice of the whole verify
    span into its pool shard, walks the per-slot paged history for its
    heads only (the chunk read, batched over slots — bit-exact per
    head), and all-gathers the context before the replicated residual
    walk. Sampling runs on replicated logits with the per-step key
    folded at each ABSOLUTE position, mirroring
    :meth:`GenerationEngine._verify_impl` — so speculative streams stay
    byte-identical to solo at every TP degree."""
    import jax
    import jax.numpy as jnp

    from ..ops.attention import _NEG_BIG
    from .engine import _sample_slot_tokens

    ps = engine.page_size
    trash = engine.pool.trash_page
    top_k = engine.top_k
    mp = engine._max_pages
    max_len = engine.max_seq_len
    c = engine.draft_len + 1
    tp = int(mesh.devices.size)
    kloc = engine.pool.n_kv_heads // tp

    def verify(
        p_loc, kp, vp, toks, starts, n_valid, ptabs, temps, seeds, top_ps
    ):
        full = {**gather_tp_params(p_loc, axis), "n_heads": n_heads}
        slots = toks.shape[0]
        offs = jnp.arange(c)
        pos = starts[:, None] + offs[None, :]
        pos_c = jnp.clip(pos, 0, max_len - 1)
        state = [kp, vp]

        def attend(li, q, k, v):
            # local heads: q [S, C, n_kv, g, hd] -> [S, C, kloc, g,
            # hd]; k/v -> [S, C, kloc, hd]
            ql = _local_heads(q, axis, kloc, 2)
            kl = _local_heads(k, axis, kloc, 2)
            vl = _local_heads(v, axis, kloc, 2)
            valid = (offs[None, :] < n_valid[:, None]) & (pos < max_len)
            page = jnp.where(
                valid,
                jnp.take_along_axis(ptabs, pos_c // ps, axis=1),
                trash,
            )
            off = pos_c % ps
            state[0] = state[0].at[li, page, off].set(kl)
            state[1] = state[1].at[li, page, off].set(vl)
            hd = kl.shape[3]
            t = mp * ps
            kg = state[0][li][ptabs].reshape(slots, t, kloc, hd)
            vg = state[1][li][ptabs].reshape(slots, t, kloc, hd)
            scale = 1.0 / float(np.sqrt(hd))
            s = jnp.einsum("sckgd,stkd->sckgt", ql, kg) * scale
            visible = (
                jnp.arange(t)[None, None, :] <= pos_c[:, :, None]
            )
            s = jnp.where(visible[:, :, None, None, :], s, _NEG_BIG)
            att = jnp.einsum(
                "sckgt,stkd->sckgd", jax.nn.softmax(s, axis=-1), vg
            )
            att = jax.lax.all_gather(att, axis, axis=2, tiled=True)
            return att.reshape(slots, c, att.shape[2] * q.shape[3] * hd)

        logits = transformer_verify_chunk(
            full, toks, pos_c, attend, moe_top_k=moe_top_k
        )
        vocab = logits.shape[-1]
        u = _sample_slot_tokens(
            logits.reshape(slots * c, vocab),
            pos_c.reshape(-1),
            jnp.repeat(temps, c),
            jnp.repeat(seeds, c),
            jnp.repeat(top_ps, c),
            top_k,
        ).reshape(slots, c)
        return state[0], state[1], u

    return _wrap(verify, mesh, axis, engine._tp_param_specs, 7)


def tp_decode_impl(engine, mesh, axis: str, n_heads: int, moe_top_k: int):
    """The TP decode ``[max_slots]`` body: each shard writes its heads'
    k/v into its pool shard, runs the paged read (gather reference or
    the fused ragged kernel — both are head-batched, so the local walk
    is bit-exact) over its heads only, and all-gathers the per-head
    context. Residuals, MLP, logits, and sampling run replicated and
    match the solo decode bit-for-bit."""
    import jax
    import jax.numpy as jnp

    from ..ops import paged_attention, ragged_paged_attention

    ps = engine.page_size
    d_model = engine._d_model
    top_k = engine.top_k
    fused = engine.attention_impl == "fused"
    tp = int(mesh.devices.size)
    kloc = engine.pool.n_kv_heads // tp

    def decode(p_loc, kp, vp, toks, positions, ptabs, temps, seeds, top_ps):
        full = {**gather_tp_params(p_loc, axis), "n_heads": n_heads}
        slots = toks.shape[0]
        state = [kp, vp]

        def attend(li, q, k, v):
            ql = _local_heads(q, axis, kloc, 1)  # [S, kloc, g, hd]
            kl = _local_heads(k, axis, kloc, 1)  # [S, kloc, hd]
            vl = _local_heads(v, axis, kloc, 1)
            page = ptabs[jnp.arange(slots), positions // ps]
            off = positions % ps
            state[0] = state[0].at[li, page, off].set(kl)
            state[1] = state[1].at[li, page, off].set(vl)
            read = ragged_paged_attention if fused else paged_attention
            ctx = read(
                ql, state[0][li], state[1][li], ptabs, positions + 1
            )
            ctx = jax.lax.all_gather(ctx, axis, axis=1, tiled=True)
            return ctx.reshape(slots, d_model)

        logits = transformer_step(
            full, toks, positions, attend, moe_top_k=moe_top_k
        )
        greedy = jnp.argmax(logits, axis=-1)
        keys = jax.vmap(
            lambda s, t: jax.random.fold_in(jax.random.PRNGKey(s), t)
        )(seeds, positions)
        scaled = logits / jnp.maximum(temps[:, None], 1e-6)
        filt = filter_logits(scaled, top_k=top_k, top_p=top_ps[:, None])
        sampled = jax.vmap(jax.random.categorical)(keys, filt)
        nxt = jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
        return state[0], state[1], nxt

    return _wrap(decode, mesh, axis, engine._tp_param_specs, 6)


def estimate_collective_seconds(
    engine, mesh, axis: str
) -> Tuple[float, float]:
    """One-time micro-measurement of the per-step collective pattern:
    a jitted program that runs exactly the step's gathers — the at-rest
    weight shards back to full plus one per-layer context gather — is
    timed (one warmup, median of 3), and the engine charges the result
    to the ``serve.collective_seconds`` counter per dispatched step.
    An ESTIMATE by construction (the real gathers overlap compute
    inside the step program; XLA may also schedule them differently
    there), labeled as such in docs/observability.md. Returns
    ``(seconds_per_step, gathered_bytes_per_step)``."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map

    params = engine._params_dev
    n_layers = len(params["blocks"])
    n_kv = engine.pool.n_kv_heads
    hd = engine.pool.head_dim
    tp = int(mesh.devices.size)
    group = engine._d_model // hd // n_kv
    # GLOBAL shape; the in_spec shards the head axis to kloc per chip
    ctx_loc = jnp.zeros(
        (engine.max_slots, n_kv, group, hd), jnp.float32
    )

    def body(p_loc, ctx):
        full = gather_tp_params(p_loc, axis)
        outs = [
            jax.lax.all_gather(ctx, axis, axis=1, tiled=True)
            for _ in range(n_layers)
        ]
        # touch every gathered leaf so nothing is dead-code-eliminated
        acc = sum(jnp.sum(b["qkv"][0, 0] + b["proj"][0, 0]
                          + b["up"][0, 0] + b["down"][0, 0])
                  for b in full["blocks"])
        return acc + sum(jnp.sum(o[0, 0]) for o in outs)

    prog = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(engine._tp_param_specs, P(None, axis, None, None)),
            out_specs=P(),
            check_vma=False,
        )
    )
    try:
        jax.block_until_ready(prog(params, ctx_loc))  # compile + warm
        walls = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(prog(params, ctx_loc))
            walls.append(time.perf_counter() - t0)
        est = sorted(walls)[1]
    except Exception:
        est = 0.0
    # bytes RECEIVED per chip per step ((tp-1)/tp of each gathered
    # array), weights and per-layer context alike — one consistent unit
    gathered = 0
    frac = (tp - 1) / tp if tp > 1 else 0.0
    for b in params["blocks"]:
        for name in ("qkv", "proj", "up", "down"):
            gathered += b[name].size * b[name].dtype.itemsize * frac
    gathered += (
        n_layers * ctx_loc.size * ctx_loc.dtype.itemsize * frac
    )
    return est, float(gathered)
