"""Multi-tenant QoS plane: quotas, priorities, and SLO-actuated control.

PR 16 threaded a ``tenant`` label through ``Fleet.submit`` →
``GenRequest`` → the per-request cost ledger, but it stayed an
accounting tag: the scheduler was single-tenant FIFO and the SLO
burn-rate monitors (``obs/slo.py``) only flipped ``/healthz``. This
module turns the label into an enforcement boundary. It owns:

- the per-tenant :class:`TenantPolicy` registry (``Config.tenants`` /
  ``set_config`` / ``POST /admin/tenants``): admission quota (max
  concurrent slots + queued requests), token-bucket rate limits on
  requests/s and generated tokens/s, a priority class
  ``batch | standard | interactive``, and an optional per-tenant TTFT
  SLO surfaced on ``/statusz``;
- **admission control** (:func:`admit_request`): over-quota /
  rate-limited / shed requests raise
  :class:`~tensorframes_tpu.utils.failures.TenantThrottledError`
  (HTTP 429 with an adaptive ``Retry-After`` = the bucket's refill
  time) *before* any engine state is touched — distinct from the
  all-full 503, never retried, never replayed;
- **priority answers** for the scheduler/pool layers
  (:func:`priority_of`, :func:`clamp_spec_k`): admission ordering
  becomes (priority, arrival), ``PagePoolExhausted`` preemption becomes
  preempt-lowest-priority-then-youngest, prefix-cache eviction drops
  low-priority entries first, and speculation shrinks k for
  low-priority slots under pool pressure;
- the **SLO actuator** (:func:`slo_tick`, riding the time-series
  sampler tick right after ``slo.monitor().evaluate``): a fast burn
  sheds ``batch``-class admissions; a sustained burn deprioritizes the
  top-cost tenant (from the ``obs/requests.py`` ledger) and asks the
  fleet router to re-place its sessions onto the least-loaded replica;
  recovery re-admits. Every action increments
  ``slo.actions_total{action}`` and lands in the ``tenancy`` flight
  ring.

**The byte-identity contract is untouched.** QoS decides *which*
request runs *when* and *where* — scheduling order, preemption victims,
eviction order, placement, speculative depth — never what tokens a
request produces: any admitted stream is byte-identical to the same
request on an unloaded single-tenant engine, greedy and seeded, under
preemption, restart, and failover.

**Off is free.** With no policies configured (the default) ``_ON``
stays False — a module global refreshed by the ``set_config`` callback
hook (the TFT_OBS / chaos pattern) — and every hook returns on one
boolean check: scheduler order, preemption choice, placement, and all
emitted streams are byte-identical to the pre-tenancy engine.

See docs/serving_llm.md "Multi-tenancy".
"""

from __future__ import annotations

import dataclasses
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from ..obs import flight as _flight
from ..obs.metrics import counter as _counter
from ..obs.metrics import gauge as _gauge
from ..utils import chaos as _chaos
from ..utils.config import get_config, register_on_change, set_config
from ..utils.failures import TenantThrottledError
from ..utils.logging import get_logger

__all__ = [
    "PRIORITIES",
    "TenantPolicy",
    "admit_request",
    "apply_admin",
    "clamp_spec_k",
    "count_preemption",
    "enabled",
    "policies_view",
    "priority_of",
    "register_fleet",
    "shedding",
    "slo_tick",
    "statusz_view",
    "update_active_gauge",
]

logger = get_logger("tenancy")

#: priority classes, ordered: higher rank wins admission, lower rank is
#: preempted / shed / spec-shrunk first. Unknown tenants are
#: ``standard`` (rank 1) — exactly the single-tenant behavior.
PRIORITIES = {"batch": 0, "standard": 1, "interactive": 2}
_RANK_NAMES = {rank: name for name, rank in PRIORITIES.items()}
_DEFAULT_RANK = PRIORITIES["standard"]

#: how long a sustained-burn deprioritization of the top-cost tenant
#: holds (and the minimum spacing between successive deprioritize
#: actions — one tenant at a time, re-judged after the hold)
_DEPRI_HOLD_S = 30.0
#: Retry-After hint for SLO-shed admissions: there is no bucket to
#: compute a refill time from, so advertise the order of an SLO window
_SHED_RETRY_S = 5.0

_m_active_slots = _gauge(
    "serve.tenant_active_slots",
    "Decode slots currently held, by tenant (QoS plane on only)",
    labels=("tenant",),
)
_m_throttled = _counter(
    "serve.tenant_throttled_total",
    "Admissions refused by the QoS plane (HTTP 429), by tenant and "
    "gate (quota | rate | shed)",
    labels=("tenant", "reason"),
)
_m_preemptions = _counter(
    "serve.preemptions_total",
    "Serving preempt-and-requeues by the victim's priority class "
    "(failures.preemptions_total keeps the per-op total)",
    labels=("priority",),
)
_m_actions = _counter(
    "slo.actions_total",
    "SLO-actuated QoS control actions (shed_batch | deprioritize | "
    "replace_sessions | recover)",
    labels=("action",),
)


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's QoS contract. Every limit is optional; 0 means
    unlimited / none — a policy carrying only ``priority`` is purely a
    scheduling-class assignment."""

    tenant: str
    #: ``batch | standard | interactive`` (see :data:`PRIORITIES`)
    priority: str = "standard"
    #: admission quota: max concurrent decode slots + max queued
    #: admissions. Enforced as one bound on (active + queued) — the
    #: tenant's total footprint in the engine — because a queued
    #: request becomes an active one without re-admission.
    max_active: int = 0
    max_queued: int = 0
    #: token-bucket rate limits (sustained; burst = 1 s of rate)
    requests_per_s: float = 0.0
    tokens_per_s: float = 0.0
    #: advisory per-tenant TTFT objective, seconds — surfaced on
    #: ``/statusz`` (recent p99 vs bound from the cost ledger), not an
    #: admission gate
    ttft_slo_s: float = 0.0

    @property
    def rank(self) -> int:
        return PRIORITIES[self.priority]

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class _Bucket:
    """Token bucket: ``rate`` units/s refill, 1 s of burst. A take
    charges its full cost (the level may go negative — a single
    over-burst request is admitted against future refill, enforcing the
    *sustained* rate without deadlocking on requests larger than the
    burst), but only when the level covers ``min(cost, burst)``."""

    __slots__ = ("rate", "burst", "level", "t")

    def __init__(self, rate: float):
        self.rate = float(rate)
        self.burst = max(self.rate, 1.0)
        self.level = self.burst
        self.t = time.monotonic()

    def _refill(self, now: float) -> None:
        # a caller-supplied clock must never DRAIN the bucket (refill is
        # monotonic): clamp regressions to zero elapsed
        elapsed = max(0.0, now - self.t)
        self.level = min(self.burst, self.level + elapsed * self.rate)
        self.t = now

    def try_take(self, cost: float, now: Optional[float] = None) -> float:
        """Charge ``cost``; returns 0.0 on success, else the seconds
        until the bucket expects to cover it (the 429 Retry-After)."""
        if self.rate <= 0:
            return 0.0
        now = time.monotonic() if now is None else now
        self._refill(now)
        need = min(cost, self.burst)
        if self.level >= need:
            self.level -= cost
            return 0.0
        return (need - self.level) / self.rate


class _TenantState:
    """Mutable per-tenant runtime state beside the frozen policy."""

    __slots__ = ("req_bucket", "tok_bucket", "depri_until", "throttles")

    def __init__(self, policy: Optional[TenantPolicy]):
        self.req_bucket = _Bucket(policy.requests_per_s if policy else 0.0)
        self.tok_bucket = _Bucket(policy.tokens_per_s if policy else 0.0)
        #: monotonic deadline while the SLO actuator holds this tenant
        #: at rank 0 (sustained-burn top-cost deprioritization)
        self.depri_until = 0.0
        self.throttles: Dict[str, int] = {}


_lock = threading.Lock()
_ON = False
_policies: Dict[str, TenantPolicy] = {}
_states: Dict[str, _TenantState] = {}

#: SLO actuator state: shedding flips on the breach transition and off
#: on recovery; _next_depri_t rate-limits deprioritize actions
_shed_active = False
_next_depri_t = 0.0

#: the fleet router registered for session re-placement (weak — the
#: plane must never keep a stopped fleet alive)
_fleet_ref: "weakref.ref | None" = None


def _parse_policy(spec: Any) -> TenantPolicy:
    if isinstance(spec, TenantPolicy):
        return spec
    if not isinstance(spec, dict) or not str(spec.get("tenant", "")):
        raise ValueError(
            "each Config.tenants entry must be a dict with a non-empty "
            f"'tenant' key, got {spec!r}"
        )
    known = {f.name for f in dataclasses.fields(TenantPolicy)}
    unknown = set(spec) - known
    if unknown:
        raise ValueError(
            f"unknown tenant-policy field(s) {sorted(unknown)}; "
            f"expected a subset of {sorted(known)}"
        )
    pol = TenantPolicy(
        tenant=str(spec["tenant"]),
        priority=str(spec.get("priority", "standard")),
        max_active=int(spec.get("max_active", 0) or 0),
        max_queued=int(spec.get("max_queued", 0) or 0),
        requests_per_s=float(spec.get("requests_per_s", 0.0) or 0.0),
        tokens_per_s=float(spec.get("tokens_per_s", 0.0) or 0.0),
        ttft_slo_s=float(spec.get("ttft_slo_s", 0.0) or 0.0),
    )
    if pol.priority not in PRIORITIES:
        raise ValueError(
            f"tenant {pol.tenant!r}: priority must be one of "
            f"{sorted(PRIORITIES)}, got {pol.priority!r}"
        )
    if pol.max_active < 0 or pol.max_queued < 0:
        raise ValueError(f"tenant {pol.tenant!r}: quotas must be >= 0")
    if pol.requests_per_s < 0 or pol.tokens_per_s < 0 or pol.ttft_slo_s < 0:
        raise ValueError(f"tenant {pol.tenant!r}: rates must be >= 0")
    return pol


def _refresh() -> None:
    """Rebuild the policy registry from the live config (the
    ``register_on_change`` hook). Bucket/throttle state survives for
    tenants whose policy persists across the change *unless their rates
    changed* (a retuned limit starts from a full bucket); removed
    tenants drop. With no policies the whole plane turns off and the
    actuator state resets."""
    global _ON, _shed_active
    policies = {}
    for spec in get_config().tenants or ():
        pol = _parse_policy(spec)
        policies[pol.tenant] = pol
    with _lock:
        for name in list(_states):
            if name not in policies:
                del _states[name]
                continue
            old = _policies.get(name)
            new = policies[name]
            if old is None or (
                old.requests_per_s != new.requests_per_s
                or old.tokens_per_s != new.tokens_per_s
            ):
                _states[name] = _TenantState(new)
        _policies.clear()
        _policies.update(policies)
        if not policies:
            _shed_active = False
    _ON = bool(policies)


register_on_change(_refresh)


def enabled() -> bool:
    """True when any tenant policy is configured (the plane is live)."""
    return _ON


def shedding() -> bool:
    """True while the SLO actuator is shedding batch-class admissions."""
    return _shed_active


def _state(tenant: str) -> _TenantState:
    """The tenant's runtime state, created lazily (callers hold no
    policy requirement: the actuator can deprioritize an unregistered
    tenant). Callers must hold ``_lock``."""
    st = _states.get(tenant)
    if st is None:
        st = _states[tenant] = _TenantState(_policies.get(tenant))
    return st


def priority_of(tenant: str) -> int:
    """The tenant's effective scheduling rank right now: the policy's
    class, forced to 0 (batch) while the SLO actuator holds a
    deprioritization on it. Rank 1 (standard) with the plane off or for
    unknown tenants — the exact single-tenant behavior."""
    if not _ON:
        return _DEFAULT_RANK
    with _lock:
        pol = _policies.get(tenant)
        st = _states.get(tenant)
        if st is not None and st.depri_until > time.monotonic():
            return 0
        return pol.rank if pol is not None else _DEFAULT_RANK


def admit_request(
    tenant: str,
    new_tokens: int,
    active: int,
    queued: int,
) -> None:
    """The admission gate, called once per request at the submission
    boundary (engine front door or fleet router — never on the fleet →
    replica relay, preemption requeues, or failover replays, so a
    request is charged exactly once). ``active``/``queued`` are the
    tenant's current footprint. Raises
    :class:`~tensorframes_tpu.utils.failures.TenantThrottledError`
    (→ HTTP 429) when the tenant is shed, over quota, or rate-limited;
    returns silently otherwise. No-op with the plane off."""
    _chaos.site("tenancy.admit")
    if not _ON:
        return
    tenant = str(tenant or "")
    with _lock:
        pol = _policies.get(tenant)
        st = _state(tenant)
        now = time.monotonic()
        rank = 0 if st.depri_until > now else (
            pol.rank if pol is not None else _DEFAULT_RANK
        )
        if _shed_active and rank <= PRIORITIES["batch"]:
            verdict = ("shed", _SHED_RETRY_S)
        elif pol is not None and (pol.max_active or pol.max_queued) and (
            active + queued >= pol.max_active + pol.max_queued
        ):
            verdict = ("quota", 1.0)
        else:
            wait = st.req_bucket.try_take(1.0, now)
            if wait <= 0.0:
                wait = st.tok_bucket.try_take(float(new_tokens), now)
            verdict = ("rate", wait) if wait > 0.0 else None
        if verdict is None:
            return
        reason, retry_after = verdict
        st.throttles[reason] = st.throttles.get(reason, 0) + 1
    _m_throttled.inc(tenant=tenant or "-", reason=reason)
    _flight.record(
        "tenancy", "throttle", tenant=tenant, reason=reason,
        retry_after_s=round(retry_after, 3),
    )
    raise TenantThrottledError(
        f"tenant {tenant!r} throttled ({reason}); retry in "
        f"{retry_after:.1f}s",
        retry_after=retry_after, reason=reason, tenant=tenant,
    )


def count_preemption(rank: int) -> None:
    """Book one serving preemption under the victim's priority class
    (``serve.preemptions_total{priority}``). Counted whether or not
    the plane is on — preemptions are rare and the class label is the
    whole point of the series."""
    _m_preemptions.inc(
        priority=_RANK_NAMES.get(int(rank), str(int(rank)))
    )


def clamp_spec_k(
    k: int, rank: int, pages_free: int, pages_total: int
) -> int:
    """Priority-weighted speculative depth: under KV-pool pressure
    (less than a quarter of pages free) low-priority slots give up
    their speculative page appetite first — batch drops to k=1,
    standard to k=2, interactive keeps its adaptive k. Speculation
    depth never changes emitted bytes (exact-match acceptance), only
    how many pages a slot's burst may touch. Identity with the plane
    off."""
    if not _ON or k <= 1:
        return k
    if pages_total <= 0 or pages_free * 4 >= pages_total:
        return k
    if rank <= PRIORITIES["batch"]:
        return 1
    if rank == PRIORITIES["standard"]:
        return min(k, 2)
    return k


def update_active_gauge(slots) -> None:
    """Refresh ``serve.tenant_active_slots{tenant}`` from the
    scheduler's live slot list (engine gauge sweep; plane-on only —
    the caller gates). Tenants seen before but idle now are zeroed so
    the gauge decays instead of pinning its last busy value."""
    counts: Dict[str, int] = {}
    for act in slots:
        if act is not None:
            key = act.req.tenant or "-"
            counts[key] = counts.get(key, 0) + 1
    with _lock:
        known = {name or "-" for name in _states}
    for name in known | set(counts):
        _m_active_slots.set(float(counts.get(name, 0)), tenant=name)


def register_fleet(fleet) -> None:
    """Let the SLO actuator re-place a deprioritized tenant's sessions
    (``fleet.replace_tenant_sessions``). Weakly referenced; passing
    ``None`` (or the fleet dying) unregisters."""
    global _fleet_ref
    _fleet_ref = None if fleet is None else weakref.ref(fleet)


def _top_cost_tenant() -> Optional[str]:
    """The most expensive tenant over the recent cost-ledger window
    (sum of est_flops, tokens as tie-break) — the sustained-burn
    deprioritization target. None when the ledger is empty or every
    row is tenant-less."""
    from ..obs import requests as _obs_requests

    flops: Dict[str, float] = {}
    tokens: Dict[str, int] = {}
    for row in _obs_requests.recent():
        tenant = str(row.get("tenant") or "")
        if not tenant:
            continue
        flops[tenant] = flops.get(tenant, 0.0) + float(
            row.get("est_flops") or 0.0
        )
        tokens[tenant] = tokens.get(tenant, 0) + int(row.get("tokens") or 0)
    if not flops:
        return None
    return max(flops, key=lambda t: (flops[t], tokens.get(t, 0), t))


def _act(action: str, **fields) -> None:
    _m_actions.inc(action=action)
    _flight.record("tenancy", action, **fields)


def slo_tick(now: Optional[float] = None) -> None:
    """The SLO actuator, riding every sampler tick immediately after
    ``slo.monitor().evaluate`` (obs/timeseries.sample_once). Reads the
    burn state and *acts*:

    - any objective breached, shedding off → turn shedding ON
      (``batch``-class admissions 429 until recovery);
    - a *sustained* burn (slow window burning too) → deprioritize the
      top-cost tenant for :data:`_DEPRI_HOLD_S` seconds (rate-limited
      to one action per hold) and ask the registered fleet to re-place
      that tenant's pinned sessions onto the least-loaded replica;
    - nothing breached, shedding on → recover (re-admit).

    One boolean check with the plane off. ``now`` is accepted for
    signature symmetry with the other sampler duties; holds use the
    monotonic clock."""
    global _shed_active, _next_depri_t
    if not _ON:
        return
    from ..obs import slo as _slo

    rows = _slo.monitor().status()
    breached = [r for r in rows if r.get("breached")]
    mono = time.monotonic()
    if breached and not _shed_active:
        _shed_active = True
        _act(
            "shed_batch",
            slos=[r.get("name") for r in breached],
        )
    elif not breached and _shed_active:
        _shed_active = False
        _act("recover")
    sustained = [
        r for r in breached if r.get("severity") == "sustained"
    ]
    if sustained and mono >= _next_depri_t:
        tenant = _top_cost_tenant()
        if tenant is not None:
            _next_depri_t = mono + _DEPRI_HOLD_S
            with _lock:
                _state(tenant).depri_until = mono + _DEPRI_HOLD_S
            _act(
                "deprioritize", tenant=tenant,
                hold_s=_DEPRI_HOLD_S,
                slos=[r.get("name") for r in sustained],
            )
            fleet = _fleet_ref() if _fleet_ref is not None else None
            if fleet is not None:
                try:
                    moved = fleet.replace_tenant_sessions(tenant)
                except Exception:
                    logger.warning(
                        "session re-placement for tenant %r failed",
                        tenant, exc_info=True,
                    )
                else:
                    if moved:
                        _act(
                            "replace_sessions", tenant=tenant,
                            sessions=moved,
                        )


def policies_view() -> List[Dict[str, Any]]:
    """The live policy registry as JSON-ready dicts (``GET
    /admin/tenants``)."""
    with _lock:
        return [
            _policies[name].as_dict() for name in sorted(_policies)
        ]


def apply_admin(payload: Any) -> List[Dict[str, Any]]:
    """Apply a ``POST /admin/tenants`` body and return the resulting
    registry view. Three shapes:

    - a single policy object → upsert that tenant;
    - ``{"tenant": NAME, "delete": true}`` → remove it;
    - ``{"tenants": [...]}`` → replace the whole registry (``[]``
      turns the plane off).

    Validation errors raise ``ValueError`` (→ HTTP 400) before any
    state changes; the accepted set lands via ``set_config`` so every
    ``register_on_change`` consumer sees it atomically."""
    if not isinstance(payload, dict):
        raise ValueError("body must be a JSON object")
    if "tenants" in payload:
        new = [ _parse_policy(s).as_dict() for s in payload["tenants"] ]
    elif payload.get("delete"):
        name = str(payload.get("tenant") or "")
        if not name:
            raise ValueError("delete needs a 'tenant' name")
        new = [p for p in policies_view() if p["tenant"] != name]
    else:
        pol = _parse_policy(
            {k: v for k, v in payload.items() if k != "delete"}
        )
        new = [
            p for p in policies_view() if p["tenant"] != pol.tenant
        ] + [pol.as_dict()]
    set_config(tenants=tuple(new))
    return policies_view()


def _ledger_fold() -> Dict[str, Dict[str, Any]]:
    """Per-tenant aggregation of the recent cost-ledger ring —
    read-side only, no new bookkeeping."""
    from ..obs import requests as _obs_requests

    out: Dict[str, Dict[str, Any]] = {}
    rows = _obs_requests.recent()
    for row in rows:
        tenant = str(row.get("tenant") or "") or "-"
        agg = out.setdefault(
            tenant,
            {
                "requests": 0, "tokens": 0, "est_flops": 0.0,
                "ttft_s": [], "_ts": [],
            },
        )
        agg["requests"] += 1
        agg["tokens"] += int(row.get("tokens") or 0)
        agg["est_flops"] += float(row.get("est_flops") or 0.0)
        ttft = (
            float(row.get("queue_wait_s") or 0.0)
            + float(row.get("prefill_s") or 0.0)
        )
        if ttft > 0:
            agg["ttft_s"].append(ttft)
        try:
            agg["_ts"].append(float(row["ts"]))
        except (KeyError, TypeError, ValueError):
            pass
    for agg in out.values():
        ts = agg.pop("_ts")
        span = (max(ts) - min(ts)) if len(ts) >= 2 else 0.0
        agg["tokens_per_s"] = (
            round(agg["tokens"] / span, 3) if span > 0 else None
        )
        ttfts = sorted(agg.pop("ttft_s"))
        agg["ttft_p99_s"] = (
            round(ttfts[min(len(ttfts) - 1,
                            int(0.99 * len(ttfts)))], 6)
            if ttfts else None
        )
    return out


def statusz_view(engine=None) -> Optional[Dict[str, Any]]:
    """The ``/statusz`` per-tenant block: policies, live footprint
    (active slots + queue share from the scheduler, duck-typed through
    an engine or fleet), recent ledger throughput/cost, throttle and
    actuator state. None with the plane off (the page stays byte-
    identical to pre-tenancy)."""
    if not _ON:
        return None
    active: Dict[str, int] = {}
    queued: Dict[str, int] = {}
    counts_fn = getattr(engine, "tenant_counts", None)
    if counts_fn is None:
        sched = getattr(engine, "scheduler", None)
        counts_fn = getattr(sched, "tenant_counts", None)
    if counts_fn is not None:
        try:
            active, queued = counts_fn()
        except Exception:  # pragma: no cover - defensive
            active, queued = {}, {}
    ledger = _ledger_fold()
    mono = time.monotonic()
    with _lock:
        names = sorted(
            set(_policies) | set(_states) | set(active) | set(queued)
            | {n for n in ledger if n != "-"}
        )
        tenants = []
        for name in names:
            pol = _policies.get(name)
            st = _states.get(name)
            row: Dict[str, Any] = {
                "tenant": name,
                "priority": pol.priority if pol else "standard",
                "active_slots": int(active.get(name, 0)),
                "queued": int(queued.get(name, 0)),
                "throttles": dict(st.throttles) if st else {},
                "deprioritized": bool(
                    st and st.depri_until > mono
                ),
            }
            if pol is not None:
                row["policy"] = pol.as_dict()
            row.update(
                ledger.get(
                    name,
                    {"requests": 0, "tokens": 0, "est_flops": 0.0,
                     "tokens_per_s": None, "ttft_p99_s": None},
                )
            )
            if pol is not None and pol.ttft_slo_s > 0:
                p99 = row.get("ttft_p99_s")
                row["ttft_slo_s"] = pol.ttft_slo_s
                row["ttft_slo_ok"] = (
                    None if p99 is None else p99 <= pol.ttft_slo_s
                )
            tenants.append(row)
    return {"shedding": _shed_active, "tenants": tenants}


def _reset_for_tests() -> None:
    """Drop all runtime state (buckets, holds, shedding, fleet ref) —
    test isolation. Policies still come from the live config."""
    global _shed_active, _next_depri_t, _fleet_ref
    with _lock:
        _states.clear()
        _shed_active = False
        _next_depri_t = 0.0
    _fleet_ref = None
    _refresh()
