"""Local execution engine: the nine-function public API over one device."""

from .ops import (
    map_blocks,
    precompile,
    map_rows,
    reduce_blocks,
    reduce_rows,
    aggregate,
    analyze,
    print_schema,
    explain,
    block,
    row,
)
from .validation import (
    InputNotFoundError,
    InvalidTypeError,
    InvalidDimensionError,
    OutputCollisionError,
)

__all__ = [
    "map_blocks",
    "precompile",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "aggregate",
    "analyze",
    "print_schema",
    "explain",
    "block",
    "row",
    "InputNotFoundError",
    "InvalidTypeError",
    "InvalidDimensionError",
    "OutputCollisionError",
]
