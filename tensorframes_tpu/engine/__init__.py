"""Local execution engine: the nine-function public API over one device."""

from . import plan as plan  # logical-plan layer (registers its metrics)
from .ops import (
    map_blocks,
    precompile,
    map_rows,
    reduce_blocks,
    reduce_rows,
    aggregate,
    analyze,
    print_schema,
    explain,
    block,
    row,
)
from .validation import (
    InputNotFoundError,
    InvalidTypeError,
    InvalidDimensionError,
    OutputCollisionError,
)
from .jobs import (
    BlockLedger,
    JobResult,
    QuarantinedBlock,
    load_quarantine,
    resume_job,
    run_job,
)
from .dist_jobs import (
    WorkerReport,
    journal_status,
    run_worker,
    wait_job,
)

__all__ = [
    "BlockLedger",
    "JobResult",
    "QuarantinedBlock",
    "WorkerReport",
    "journal_status",
    "load_quarantine",
    "resume_job",
    "run_job",
    "run_worker",
    "wait_job",
    "map_blocks",
    "precompile",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "aggregate",
    "analyze",
    "print_schema",
    "explain",
    "block",
    "row",
    "InputNotFoundError",
    "InvalidTypeError",
    "InvalidDimensionError",
    "OutputCollisionError",
]
