"""Durable batch jobs: block-level journaling, crash-resume, quarantine.

The reference never solved batch fault tolerance itself — it rode Spark's
lineage-based task retry and executor blacklisting (SURVEY §5), and this
reproduction dropped that layer with Spark. ``utils/failures.py`` covers
*in-process* faults (transient retry, OOM degrade), but a process crash
still lost the whole job, and one deterministically-failing block killed
everything around it. This module is the missing durability layer:

- **journal**: a job is an engine op (``map_rows`` / ``map_blocks`` /
  ``reduce_blocks`` / ``aggregate``) executed against a
  :class:`BlockLedger`. The ledger writes a small on-disk *manifest*
  (job id, op, graph/schema fingerprint, row count, the block plan) and,
  as each block completes, spools its results (npz) and appends a
  completion record to an append-only ``ledger.jsonl`` — npz first
  (atomic rename), record second, so a crash at any instant leaves a
  readable journal. Durability model: every completed ``write()``
  survives *process death* (the threat the journal exists for — a
  kill-9'd job resumes losing only blocks whose records had not landed,
  which recompute); against a whole-OS crash the fsync'd manifest and
  final completion marker survive and any torn tail (unparseable
  ledger line, unreadable npz) is detected on resume and simply
  recomputes. Per-block fsyncs are deliberately NOT issued, and block
  records are written by a background journal thread so a block's disk
  I/O overlaps the next block's compute (the decode-prefetch idiom) —
  both are what keeps journaling inside the ≤ 5% overhead budget while
  buying nothing less against process death.
- **crash-resume**: :func:`resume_job` replays the journal and re-runs
  the op; blocks with completion records are *restored* from their
  spools and only unfinished blocks recompute. The block plan is
  deterministic (partition bounds / fixed row chunks in a fixed bucket
  order), so a resumed job's output is byte-identical to a clean run.
  Dense ``map_rows`` plans are additionally ALIGNED to the streaming
  transfer layer's chunk quantum (``frame/transfer.py``: block rows =
  ``min(max_rows_per_device_call, transfer_chunk_bytes // row_bytes)``),
  and feeds cross the link per block — a resumed job re-uploads exactly
  its unfinished blocks' bytes, never the completed ones
  (tests/test_jobs.py asserts on the ``frame.h2d_bytes_total`` delta).
- **quarantine**: a block whose program fails *deterministically*
  (non-transient, non-OOM after retries — the Spark-blacklisting
  analogue) is recorded with the real error in ``quarantine.json``,
  skipped, and the job continues. The partial result surfaces as
  ``JobResult.completed`` + ``JobResult.quarantined``; strict mode
  raises :class:`~tensorframes_tpu.utils.failures.QuarantinedBlocksError`
  at job end instead (healthy blocks are still journaled first).
  Transient and OOM failures are *never* quarantined — they are
  capacity/infrastructure conditions: the job fails and resumes later.

Journal layout (``<job_dir>/<job_id>/``)::

    manifest.json                   job id, op, fingerprint, row count, plan
    blocks/block-00007.npz          spooled fetch arrays for block 7
    ledger.jsonl                    append-only completion / quarantine log
    quarantine.json                 current quarantined blocks + errors
    leases/block-00007.e000002.lease  block 7's lease at fencing epoch 2
    leases/journal.e000000.lease    journal-level lease (resume/assembly)

The ``leases/`` directory belongs to the **distributed** drain layer
(``engine/dist_jobs.py``): K independent worker processes attach to one
journal and drain one manifest concurrently, coordinator-free — atomic
per-block leasing (O_EXCL epoch files), heartbeat renewal, dead-worker
reclamation (epoch bump + byte-identical recompute, exactly the resume
path), and **write fencing**: every spool write and ledger append
carries the writer's ``(worker_id, epoch)``, a zombie whose lease was
stolen fails its late write with
:class:`~tensorframes_tpu.utils.failures.StaleLeaseError`, and replay
ignores any done-record superseded by a higher epoch. Single-process
jobs never create ``leases/``; ``resume_job`` takes the journal-level
lease so a resume cannot race an active distributed drain.

Chaos sites ``jobs.block`` (per-block execution — a ``fatal`` kind is
the poison-block drill) and ``jobs.journal_write`` (the spool+append
path — a ``fatal`` there simulates a crash between computing a block
and recording it) drive the whole subsystem under the deterministic
harness — plus ``jobs.lease`` / ``jobs.heartbeat`` on the distributed
paths; see docs/fault_tolerance.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import (
    TraceContext,
    current_trace as _current_trace,
    flight as _flight,
    new_trace as _new_trace,
    span as _span,
    use_trace as _use_trace,
)
from ..obs.metrics import counter as _counter
from ..utils import get_logger
from ..utils.failures import (
    QuarantinedBlocksError,
    first_line,
    is_oom,
    is_transient,
    run_with_retries,
)

__all__ = [
    "BlockLedger",
    "JobResult",
    "QuarantinedBlock",
    "jobs_status",
    "load_quarantine",
    "resume_job",
    "run_job",
]

logger = get_logger("jobs")

_m_blocks = _counter(
    "jobs.blocks_total",
    "Batch-job blocks by terminal status (computed fresh, restored from "
    "the journal, quarantined)",
    labels=("status",),
)
_m_resumes = _counter(
    "jobs.resumes_total", "Batch jobs resumed from an on-disk journal"
)
_m_quarantined = _counter(
    "jobs.quarantined_total", "Blocks quarantined across all batch jobs"
)
_m_fence_rejects = _counter(
    "jobs.fence_rejects_total",
    "Journal writes rejected by the lease fence: a worker whose block "
    "lease was reclaimed (stale epoch) tried to record late, or a "
    "superseded record was ignored on replay",
)

_OPS = ("map_rows", "map_blocks", "reduce_blocks", "aggregate")

_MANIFEST = "manifest.json"
_LEDGER = "ledger.jsonl"
_QUARANTINE = "quarantine.json"
_BLOCK_DIR = "blocks"
#: spooled-array key prefix inside a block npz (keeps fetch names out of
#: np.savez's own parameter namespace — a fetch named "file" is legal)
_SPOOL_PREFIX = "c_"


def _default_job_dir() -> str:
    return os.environ.get("TFT_JOB_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "tensorframes_tpu", "jobs"
    )


def _atomic_write(path: str, data: bytes) -> None:
    # unique tmp name: concurrent distributed workers may write the
    # same manifest (identical content) at the same instant, and a
    # shared tmp path would make one rename fail under the other
    tmp = f"{path}.tmp-{uuid.uuid4().hex[:8]}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


@dataclasses.dataclass
class QuarantinedBlock:
    """One poisoned block: its plan position, the real error, and the
    flight recorder's debug bundle for the failure (``debug_bundle`` —
    a path on the quarantining host; empty when observability was off
    or the dump failed)."""

    index: int
    rows: Optional[int]
    error_type: str
    error: str
    traceback: str = ""
    debug_bundle: str = ""

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "QuarantinedBlock":
        return cls(
            index=int(d["index"]),
            rows=d.get("rows"),
            error_type=d.get("error_type", ""),
            error=d.get("error", ""),
            traceback=d.get("traceback", ""),
            debug_bundle=d.get("debug_bundle", ""),
        )


@dataclasses.dataclass
class JobResult:
    """Outcome of a (possibly partial) batch job.

    ``completed`` is the op's result built from every non-quarantined
    block — a :class:`~tensorframes_tpu.frame.TensorFrame` for the maps
    and ``aggregate``, the reduce value for ``reduce_blocks`` (``None``
    when every block quarantined). ``quarantined`` lists the poisoned
    blocks with their real errors; call :meth:`raise_if_quarantined` (or
    run strict) to turn a partial result into an exception."""

    job_id: str
    op: str
    path: Optional[str]
    completed: Any
    quarantined: List[QuarantinedBlock]
    resumed: bool
    blocks_total: int
    blocks_computed: int
    blocks_restored: int

    def raise_if_quarantined(self) -> "JobResult":
        if self.quarantined:
            raise QuarantinedBlocksError(
                _quarantine_message(self.job_id, self.quarantined),
                self.quarantined,
            )
        return self


def _quarantine_message(job_id: str, blocks: List[QuarantinedBlock]) -> str:
    head = ", ".join(
        f"block {b.index} ({b.error_type}: {b.error.splitlines()[0][:120] if b.error else ''})"
        for b in blocks[:3]
    )
    more = f" (+{len(blocks) - 3} more)" if len(blocks) > 3 else ""
    return (
        f"job {job_id}: {len(blocks)} block(s) quarantined after "
        f"deterministic failures: {head}{more}"
    )


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


class BlockLedger:
    """Per-job journal + per-block completion/quarantine bookkeeping.

    The engine's block loops (``engine/ops.py``) drive it with three
    calls: :meth:`ensure_plan` once (write on a fresh job, validate on
    resume), then per block :meth:`lookup` (restored / quarantined /
    todo) and :meth:`run_block` (execute, classify failures, spool).
    ``path=None`` is the in-memory mode: the same block loop and
    quarantine semantics with zero disk I/O (overhead baselines, tests,
    ``run_job(journal=False)``)."""

    def __init__(self, path: Optional[str], job_id: str, op: str):
        if op not in _OPS:
            raise ValueError(f"unknown job op {op!r}; expected one of {_OPS}")
        self.path = path
        self.job_id = job_id
        self.op = op
        self._plan: Optional[List[Dict[str, Any]]] = None
        self._manifest: Optional[Dict[str, Any]] = None
        #: block index -> spool relpath (disk, lazily loaded) or the
        #: result arrays themselves (memory mode / after load)
        self._done: Dict[int, Any] = {}
        #: block index -> fencing epoch of its surviving done-record
        #: (0 for single-process records, which carry no tag)
        self._done_epoch: Dict[int, int] = {}
        self._quar: Dict[int, QuarantinedBlock] = {}
        self._restored = 0
        self._computed = 0
        self._complete = False
        #: the job's TraceContext: stamped into the manifest on a fresh
        #: job, adopted FROM the manifest on resume/attach — one
        #: trace_id follows the job across processes, workers, and
        #: epochs (docs/observability.md)
        self._trace: Optional[TraceContext] = None
        #: block index -> (trace_id, span_id) of its jobs.block span,
        #: stamped into the block's ledger record so the journal alone
        #: reconstructs which trace computed what
        self._block_trace: Dict[int, Tuple[Optional[str], Optional[str]]] = {}
        self._ledger_file = None
        #: background journal writer: block i's spool overlaps block
        #: i+1's compute (the decode-prefetch idiom); errors park in
        #: _writer_error and surface at the next block / finalize
        self._write_q = None
        self._writer: Optional[threading.Thread] = None
        self._writer_error: Optional[BaseException] = None

    # -- construction ------------------------------------------------------

    @classmethod
    def create(
        cls, path: Optional[str], job_id: str, op: str
    ) -> "BlockLedger":
        """A fresh ledger. With a path, the journal directory is created
        (and must not already hold a manifest — jobs never silently
        overwrite each other's journals)."""
        led = cls(path, job_id, op)
        if path is not None:
            os.makedirs(os.path.join(path, _BLOCK_DIR), exist_ok=True)
            if os.path.exists(os.path.join(path, _MANIFEST)):
                raise ValueError(
                    f"journal directory {path!r} already holds a job "
                    f"manifest; use resume_job() to continue it or pick "
                    f"a fresh job_id"
                )
        return led

    @classmethod
    def open_(cls, path: str) -> "BlockLedger":
        """Load an existing journal for resume. Torn tail lines in
        ``ledger.jsonl`` (a crash mid-append) are ignored; a completion
        record whose npz spool is missing or unreadable is dropped and
        its block recomputes."""
        with open(os.path.join(path, _MANIFEST), "rb") as f:
            manifest = json.loads(f.read().decode("utf-8"))
        led = cls(path, manifest["job_id"], manifest["op"])
        led._manifest = manifest
        led._plan = manifest["plan"]
        if manifest.get("trace_id"):
            # continue the original run's trace (resumes and distributed
            # workers parent their spans to the job's root)
            led._trace = TraceContext(
                manifest["trace_id"],
                manifest.get("trace_span_id") or "0" * 16,
            )
        try:
            with open(os.path.join(path, _LEDGER), "rb") as f:
                lines = f.read().decode("utf-8", "replace").splitlines()
        except FileNotFoundError:
            lines = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                break  # torn tail write; everything before it is valid
            if rec.get("event") == "complete":
                led._complete = True
            elif rec.get("event") == "quarantine_cleared":
                led._quar.clear()
            elif rec.get("status") == "done":
                blk = int(rec["block"])
                epoch = int(rec.get("epoch", 0))
                prev = led._done_epoch.get(blk)
                if prev is not None and epoch < prev:
                    # replay-side fence: a zombie's late append that
                    # slipped past the write fence is superseded by the
                    # reclaimer's higher-epoch record (both byte-
                    # identical by determinism; the arbitration keeps
                    # the journal's story single-writer per block)
                    _m_fence_rejects.inc()
                    _flight.record(
                        "fences", "stale_record", job=led.job_id,
                        block=blk, epoch=epoch, superseded_by=prev,
                        worker=str(rec.get("worker")),
                    )
                    logger.warning(
                        "job %s: ignoring stale done-record for block %d "
                        "(epoch %d < %d, worker %s)",
                        led.job_id, blk, epoch, prev, rec.get("worker"),
                    )
                    continue
                spool = os.path.join(path, rec["npz"])
                if os.path.exists(spool):
                    led._done[blk] = rec["npz"]
                    led._done_epoch[blk] = epoch
                else:
                    logger.warning(
                        "job %s: block %s has a completion record but no "
                        "spool at %s; it will recompute",
                        led.job_id, rec.get("block"), rec["npz"],
                    )
            elif rec.get("status") == "quarantined":
                led._quar[int(rec["block"])] = QuarantinedBlock.from_dict(rec)
        return led

    # -- plan --------------------------------------------------------------

    @staticmethod
    def _fingerprint(
        op: str,
        graph,
        schema,
        rows: int,
        extra: Optional[Dict[str, Any]],
    ) -> str:
        """Structural job fingerprint: op, placeholder specs, fetch
        names, input schema, row count. It validates that a resume is
        re-running *the same job shape*; program bytes are not hashed —
        supplying a different computation with an identical signature is
        the caller's contract, same as Spark's assumption that a re-run
        closure matches its lineage."""
        import hashlib

        payload: Dict[str, Any] = {
            "op": op,
            "rows": int(rows),
            "extra": extra or {},
        }
        if graph is not None:
            payload["fetches"] = list(graph.fetch_names)
            payload["placeholders"] = sorted(
                (
                    name,
                    spec.scalar_type.name,
                    [str(d) for d in spec.shape.dims],
                )
                for name, spec in graph.placeholders.items()
            )
        if schema is not None:
            payload["schema"] = [
                [c.name, c.scalar_type.name] for c in schema
            ]
        blob = json.dumps(payload, sort_keys=True).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()

    def ensure_plan(
        self,
        entries: List[Dict[str, Any]],
        *,
        graph=None,
        schema=None,
        rows: int = 0,
        extra: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Install the block plan. Fresh job: write the manifest.
        Resume: validate the recomputed plan + fingerprint against the
        journal — a mismatched frame, program signature, or chunking
        must fail loudly, not silently splice wrong spools into the
        output."""
        entries = json.loads(json.dumps(entries))  # normalize numerics
        fp = self._fingerprint(self.op, graph, schema, rows, extra)
        if self._manifest is not None:
            if self._manifest.get("fingerprint") != fp:
                raise ValueError(
                    f"journal at {self.path!r} was written for a "
                    f"different job (op/program/schema/row-count "
                    f"fingerprint mismatch); resume_job must be given "
                    f"the same fetches and input frame"
                )
            if self._manifest.get("plan") != entries:
                raise ValueError(
                    f"journal at {self.path!r} holds a different block "
                    f"plan ({len(self._manifest.get('plan', []))} blocks "
                    f"vs {len(entries)} now); the frame's partitioning/"
                    f"chunking changed since the job was journaled"
                )
            return
        self._plan = entries
        self._manifest = {
            "version": 1,
            "job_id": self.job_id,
            "op": self.op,
            "created_unix": time.time(),
            "rows": int(rows),
            "fingerprint": fp,
            "plan": entries,
        }
        if self.path is not None and self._trace is not None:
            # two workers can race a FRESH journal: both attach before
            # either wrote the manifest, both mint a trace. Re-read the
            # disk here and adopt a winner's trace so the concurrent
            # manifest writes stay identical and the job converges on
            # ONE trace_id (a loser's pre-adoption claim events keep
            # its minted id — the residual window is one read+write)
            try:
                with open(os.path.join(self.path, _MANIFEST)) as f:
                    prev = json.load(f)
                if prev.get("trace_id"):
                    self._trace = TraceContext(
                        prev["trace_id"],
                        prev.get("trace_span_id") or "0" * 16,
                    )
            except (OSError, ValueError):
                pass
        if self._trace is not None:
            # NOT part of the fingerprint: a resume with the same job
            # shape must validate regardless of which trace started it
            self._manifest["trace_id"] = self._trace.trace_id
            self._manifest["trace_span_id"] = self._trace.span_id
        if self.path is not None:
            self._journal_write(
                lambda: _atomic_write(
                    os.path.join(self.path, _MANIFEST),
                    json.dumps(self._manifest, indent=1).encode("utf-8"),
                ),
                what="jobs manifest-write",
            )

    # -- per-block ---------------------------------------------------------

    def peek(self, i: int) -> str:
        """Block status WITHOUT restoring: ``"done"`` / ``"quarantined"``
        / ``"todo"``. Side-effect-free (no spool load, no counters) —
        prefetchers use it to skip work for blocks that will never
        recompute."""
        if i in self._quar:
            return "quarantined"
        return "done" if i in self._done else "todo"

    def lookup(
        self, i: int
    ) -> Tuple[str, Optional[Dict[str, np.ndarray]]]:
        """``("done", arrays)`` for a journaled block (restored from its
        spool), ``("quarantined", None)``, or ``("todo", None)``."""
        if i in self._quar:
            return "quarantined", None
        hit = self._done.get(i)
        if hit is None:
            return "todo", None
        if isinstance(hit, str):  # disk spool; loaded, NOT cached — the
            # caller consumes the arrays into its own accumulation and
            # never looks the block up again this run, so caching here
            # would duplicate the whole job output in host memory
            try:
                with np.load(
                    os.path.join(self.path, hit), allow_pickle=True
                ) as z:
                    hit = {
                        k[len(_SPOOL_PREFIX):]: z[k] for k in z.files
                    }
            except Exception:
                logger.warning(
                    "job %s: spool for block %d is unreadable; "
                    "recomputing", self.job_id, i, exc_info=True,
                )
                del self._done[i]
                return "todo", None
        self._restored += 1
        _m_blocks.inc(status="restored")
        return "done", hit

    def run_block(
        self,
        i: int,
        compute: Callable[[], Dict[str, np.ndarray]],
        rows: Optional[int] = None,
    ) -> Optional[Dict[str, np.ndarray]]:
        """Execute one block and journal the outcome. Returns the result
        arrays, or ``None`` when the block was quarantined.

        Failure classification mirrors the taxonomy in
        ``utils/failures.py``: transient errors were already retried by
        the compute's own ``run_with_retries`` window, so a transient
        (or OOM) surfacing here is an infrastructure/capacity condition
        — the job fails and is resumable. Anything else failed
        *deterministically* and is quarantined."""
        from ..utils import chaos as _chaos

        self._check_writer()
        tid = self._trace.trace_id if self._trace is not None else None
        sid: Optional[str] = None
        try:
            with _use_trace(self._trace), _span(
                "jobs.block", job=self.job_id, block=i
            ) as sp:
                if sp is not None:
                    tid, sid = sp.trace_id, sp.span_id
                _chaos.site("jobs.block")
                res = compute()
        except Exception as e:
            self._block_trace[i] = (tid, sid)
            if is_transient(e) or is_oom(e):
                raise
            self._record_quarantine(i, e, rows)
            return None
        self._block_trace[i] = (tid, sid)
        self._record_done(i, res, rows)
        return res

    # -- distributed-drain hooks (overridden by engine/dist_jobs.py) -------

    def _writer_tag(self, i: int) -> Dict[str, Any]:
        """Identity stamped into block ``i``'s journal records. The
        distributed ledger returns ``{"worker": ..., "epoch": ...}`` —
        the write-fencing token; single-process records carry none (and
        replay treats them as epoch 0)."""
        return {}

    def _fence_check(self, i: int) -> None:
        """Write fence, called INSIDE the journal writer immediately
        before block ``i``'s spool rename + ledger append. The
        distributed ledger verifies this worker still holds block
        ``i``'s lease at its claimed epoch and raises
        :class:`~tensorframes_tpu.utils.failures.StaleLeaseError`
        otherwise; single-process jobs have nothing to fence."""

    def _on_recorded(self, i: int, done: bool = True) -> None:
        """Called INSIDE the journal writer right after block ``i``'s
        record landed — the distributed ledger settles the block's
        lease here (never earlier: a lease settled before the record
        lands would let another worker recompute and double-record).
        ``done`` distinguishes a completion record (the lease becomes a
        terminal marker) from a quarantine record (the lease is
        released so ``retry_quarantined`` drains can re-claim)."""

    def _spool_tmp_suffix(self) -> str:
        """Disambiguates spool tmp names: concurrent workers writing
        block tmp files into one ``blocks/`` directory must never share
        a tmp path (the final rename target is the same by design)."""
        return ""

    def _trace_fields(self, i: int) -> Dict[str, Any]:
        """Trace identity for block ``i``'s ledger record: the
        ``jobs.block`` span's ids when one was live, else the job-level
        trace_id alone — ``ledger.jsonl`` plus the JSONL span sink must
        reconstruct the block's story with no in-memory state."""
        tid, sid = self._block_trace.get(i, (None, None))
        if tid is None and self._trace is not None:
            tid = self._trace.trace_id
        out: Dict[str, Any] = {}
        if tid:
            out["trace_id"] = tid
        if sid:
            out["span_id"] = sid
        return out

    def _journal_write(self, fn: Callable[[], None], what: str) -> None:
        """All journal mutations funnel through here: the chaos site
        sits inside the retry window, so injected transients exercise
        the retry path and injected fatals abort the job with the
        journal still consistent (spool-then-record, both atomic)."""
        from ..utils import chaos as _chaos

        def write():
            _chaos.site("jobs.journal_write")
            fn()

        with _span("jobs.journal_write", job=self.job_id):
            run_with_retries(write, what=what)

    # -- the background writer ---------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            item = self._write_q.get()
            if item is None:
                return
            fn, what = item
            try:
                self._journal_write(fn, what=what)
            except BaseException as e:  # surfaced by _check_writer
                self._writer_error = e
                return

    def _enqueue(self, fn: Callable[[], None], what: str) -> None:
        """Hand a journal mutation to the writer thread. Writes stay
        strictly ordered (one FIFO, one writer) so spool-before-record
        holds; the block loop overlaps the next block's compute with
        this block's disk I/O — per-block journal cost leaves the
        critical path (the ≤ 5% overhead budget)."""
        self._check_writer()
        if self._write_q is None:
            import queue

            # bounded: if compute outpaces the disk, the block loop
            # backpressures instead of accumulating every pending
            # block's result arrays in the queue's closures
            self._write_q = queue.Queue(maxsize=4)
            self._writer = threading.Thread(
                target=self._writer_loop,
                name=f"tft-journal-{self.job_id}",
                daemon=True,
            )
            self._writer.start()
        import queue

        while True:
            # re-check between put attempts: a writer that died with the
            # queue full must surface its error, not deadlock the put
            self._check_writer()
            try:
                self._write_q.put((fn, what), timeout=1.0)
                return
            except queue.Full:
                continue

    def _check_writer(self) -> None:
        if self._writer_error is not None:
            e, self._writer_error = self._writer_error, None
            raise e

    def _drain_writer(self, swallow: bool = False) -> None:
        """Flush the write queue and stop the writer. ``swallow`` is the
        failure-path variant (the job is already raising; a parked
        writer error must not mask it)."""
        if self._writer is not None:
            import queue

            deadline = time.monotonic() + 60
            while self._writer.is_alive():  # a dead writer needs no stop
                try:
                    self._write_q.put(None, timeout=1.0)
                    break
                except queue.Full:
                    if time.monotonic() > deadline:
                        break
            self._writer.join(timeout=60)
            wedged = self._writer.is_alive()
            self._writer = None
            self._write_q = None
            if wedged:
                # a wedged filesystem write: never share its file handle.
                # The journal stays consistent — unrecorded blocks simply
                # recompute on resume — but the job must not claim success
                logger.warning(
                    "job %s: journal writer did not drain within 60s; "
                    "unflushed block records will recompute on resume",
                    self.job_id,
                )
                if not swallow:
                    raise RuntimeError(
                        f"job {self.job_id}: journal writer wedged "
                        f"(filesystem stall?); the job is resumable"
                    )
        if swallow:
            self._writer_error = None
        else:
            self._check_writer()

    def _append(self, rec: Dict[str, Any]) -> None:
        # one handle for the job's lifetime: open/close (let alone
        # fsync) per block costs more than a small block's compute.
        # flush() completes the write() syscall, which is all process-
        # death durability needs; a torn tail after an OS crash is
        # detected and recomputed on resume.
        f = self._ledger_file
        if f is None or f.closed:
            f = self._ledger_file = open(
                os.path.join(self.path, _LEDGER), "ab"
            )
        f.write(json.dumps(rec).encode("utf-8") + b"\n")
        f.flush()

    def _record_done(
        self, i: int, res: Dict[str, np.ndarray], rows: Optional[int]
    ) -> None:
        def counted():
            # the "computed" tally means DURABLY recorded: a block whose
            # record dies with the process recomputes on resume and must
            # not have claimed completion (the soak asserts on this)
            self._computed += 1
            _m_blocks.inc(status="computed")

        if self.path is not None:
            rel = os.path.join(_BLOCK_DIR, f"block-{i:05d}.npz")
            final = os.path.join(self.path, rel)
            # the fencing token is captured NOW (while this worker still
            # believes it owns the block); the fence re-validates it at
            # actual write time, inside the writer thread
            tag = self._writer_tag(i)
            tag.update(self._trace_fields(i))

            def write():
                self._fence_check(i)
                tmp = final + f".tmp{self._spool_tmp_suffix()}.npz"
                with open(tmp, "wb") as f:
                    # keys are prefixed so a fetch named "file" (or any
                    # other np.savez parameter name) cannot collide with
                    # savez's own signature
                    np.savez(
                        f, **{_SPOOL_PREFIX + k: v for k, v in res.items()}
                    )
                os.replace(tmp, final)
                self._append(
                    {"block": i, "status": "done", "npz": rel,
                     "rows": rows, **tag}
                )
                counted()
                self._on_recorded(i)

            self._enqueue(write, what="jobs journal-write")
            self._done[i] = rel
        else:
            counted()
            # a sentinel, not the arrays: the op keeps its own copy of
            # every block's output; retaining a second one here would
            # double peak host memory on exactly the large jobs this
            # layer exists for (an in-memory ledger can never be
            # looked up again anyway — there is nothing to resume)
            self._done[i] = True

    def _record_quarantine(
        self, i: int, e: BaseException, rows: Optional[int]
    ) -> None:
        import traceback as _tb

        qb = QuarantinedBlock(
            index=i,
            rows=rows,
            error_type=type(e).__name__,
            error=str(e),
            traceback="".join(
                _tb.format_exception(type(e), e, e.__traceback__)
            )[-4000:],
        )
        self._quar[i] = qb
        _m_blocks.inc(status="quarantined")
        _m_quarantined.inc()
        _flight.record(
            "jobs", "quarantine", job=self.job_id, block=i,
            error=f"{qb.error_type}: {first_line(qb.error)}",
        )
        # the black box for the poison block: ring contents, metrics,
        # config, chaos spec — linked from quarantine.json so the
        # post-mortem starts from load_quarantine() alone
        qb.debug_bundle = _flight.dump_bundle(
            "block_quarantine",
            # per-block debounce identity: sibling blocks poisoned
            # milliseconds apart each get their linked bundle
            debounce_key=f"{self.job_id}/{i}",
            series_prefix="jobs.",
            extra={
                "job_id": self.job_id,
                "op": self.op,
                "block": i,
                "rows": rows,
                "error_type": qb.error_type,
                "error": qb.error[:2000],
                **self._trace_fields(i),
            },
        ) or ""
        logger.error(
            "job %s: block %d failed deterministically (%s: %s); "
            "quarantined — the job continues without it",
            self.job_id, i, qb.error_type, qb.error.splitlines()[0]
            if qb.error else "",
        )
        if self.path is not None:
            tag = self._writer_tag(i)
            tag.update(self._trace_fields(i))

            def write():
                self._fence_check(i)
                self._append({"status": "quarantined", **qb.as_dict(),
                              "block": i, **tag})
                self._write_quarantine_manifest()
                self._on_recorded(i, done=False)

            self._enqueue(write, what="jobs quarantine-write")

    def _write_quarantine_manifest(self) -> None:
        _atomic_write(
            os.path.join(self.path, _QUARANTINE),
            json.dumps(
                {
                    "job_id": self.job_id,
                    "op": self.op,
                    "blocks": [
                        self._quar[k].as_dict() for k in sorted(self._quar)
                    ],
                },
                indent=1,
            ).encode("utf-8"),
        )

    def clear_quarantine(self) -> None:
        """Forget quarantine records so those blocks re-attempt
        (``resume_job(retry_quarantined=True)`` after an upstream fix)."""
        if not self._quar:
            return
        self._quar.clear()
        if self.path is not None:
            def write():
                self._append({"event": "quarantine_cleared"})
                self._write_quarantine_manifest()

            self._enqueue(write, what="jobs quarantine-clear")

    def finalize(self) -> None:
        self._drain_writer()  # all block records on disk (or raise)
        if self.path is not None and not self._complete:
            def write():
                self._append({"event": "complete"})
                # the one deliberate fsync on the whole path: a FINISHED
                # job's journal is durable against an OS crash too
                self._ledger_file.flush()
                os.fsync(self._ledger_file.fileno())

            self._journal_write(write, what="jobs complete-marker")
        if self._ledger_file is not None and not self._ledger_file.closed:
            self._ledger_file.close()
        self._complete = True

    def abort(self) -> None:
        """Failure-path cleanup: stop the writer without masking the
        in-flight error, keep everything already journaled (that is the
        point), close the handle."""
        self._drain_writer(swallow=True)
        if self._ledger_file is not None and not self._ledger_file.closed:
            self._ledger_file.close()

    # -- introspection -----------------------------------------------------

    @property
    def stored_plan(self) -> Optional[List[Dict[str, Any]]]:
        """The block plan already on record — the journaled plan when
        resuming, ``None`` for a fresh job (before ``ensure_plan``).
        Resumable ops rebuild their block loop FROM this instead of
        re-deriving it from live config, so tuning a knob that shapes
        fresh plans (``transfer_chunk_bytes``, ``transfer_dtype``,
        ``max_rows_per_device_call``) between a run and its resume
        cannot invalidate the journal."""
        return self._plan

    @property
    def quarantined(self) -> List[QuarantinedBlock]:
        return [self._quar[k] for k in sorted(self._quar)]

    @property
    def quarantined_indices(self) -> List[int]:
        return sorted(self._quar)

    @property
    def num_blocks(self) -> int:
        return len(self._plan or ())

    @property
    def computed(self) -> int:
        return self._computed

    @property
    def restored(self) -> int:
        return self._restored


def load_quarantine(path: str) -> List[QuarantinedBlock]:
    """Read a job's quarantine manifest (``quarantine.json``) without
    resuming it — the ops cookbook entry point for "what poisoned my
    job, and with which error"."""
    try:
        with open(os.path.join(path, _QUARANTINE), "rb") as f:
            data = json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        return []
    return [QuarantinedBlock.from_dict(d) for d in data.get("blocks", ())]


# ---------------------------------------------------------------------------
# in-process job registry (surfaced in /healthz)
# ---------------------------------------------------------------------------

_status_lock = threading.Lock()
_active: Dict[str, Dict[str, Any]] = {}
_totals = {"runs": 0, "completed": 0, "failed": 0, "resumes": 0}
_last: Optional[Dict[str, Any]] = None


def _register_start(ledger: BlockLedger, resumed: bool) -> None:
    with _status_lock:
        _totals["runs"] += 1
        if resumed:
            _totals["resumes"] += 1
        _active[ledger.job_id] = {
            "job_id": ledger.job_id,
            "op": ledger.op,
            "path": ledger.path,
            "resumed": resumed,
            "started_unix": time.time(),
        }


def _register_end(ledger: BlockLedger, ok: bool) -> None:
    global _last
    with _status_lock:
        info = _active.pop(ledger.job_id, {})
        info.update(
            state="complete" if ok else "failed",
            blocks_total=ledger.num_blocks,
            blocks_computed=ledger.computed,
            blocks_restored=ledger.restored,
            blocks_quarantined=len(ledger.quarantined_indices),
        )
        _totals["completed" if ok else "failed"] += 1
        _last = info


def jobs_status() -> Dict[str, Any]:
    """Point-in-time batch-job summary for this process — embedded in
    the scoring server's ``GET /healthz`` payload so operators see batch
    health next to serving health.

    For a *journaled* job (active here, or the last one finished), the
    summary additionally carries a ``"journal"`` view read from the
    journal directory itself — block progress plus the distributed
    worker/lease table (``engine/dist_jobs.py``) — so an operator
    probing ANY process's ``/healthz`` sees the whole fleet draining
    the manifest, not just this process's registry."""
    with _status_lock:
        status = {
            "active": len(_active),
            "runs_total": _totals["runs"],
            "completed_total": _totals["completed"],
            "failed_total": _totals["failed"],
            "resumes_total": _totals["resumes"],
            "last": dict(_last) if _last else None,
        }
        path = None
        for info in _active.values():
            path = info.get("path") or path
        if path is None and _last:
            path = _last.get("path")
    if path is not None:
        try:
            from .dist_jobs import journal_status

            status["journal"] = journal_status(path)
        except Exception:  # health must never fail over a disk probe
            status["journal"] = None
    return status


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def _new_job_id(op: str) -> str:
    return f"{op}-{time.strftime('%Y%m%d-%H%M%S')}-{uuid.uuid4().hex[:6]}"


def _execute(
    op: str,
    fetches,
    data,
    ledger: BlockLedger,
    trim: bool,
    feed_dict,
    constants,
):
    from . import ops as _ops

    if op == "map_rows":
        return _ops.map_rows(
            fetches, data, feed_dict=feed_dict, _ledger=ledger
        ).cache()
    if op == "map_blocks":
        return _ops.map_blocks(
            fetches, data, trim=trim, feed_dict=feed_dict,
            constants=constants, _ledger=ledger,
        ).cache()
    if op == "reduce_blocks":
        return _ops.reduce_blocks(fetches, data, _ledger=ledger)
    return _aggregate_job(fetches, data, ledger)


def _aggregate_job(fetches, grouped, ledger: BlockLedger):
    """``aggregate`` executes as one coarse work unit: its segmented
    scan has no block loop to journal, so the job records a single
    completion with the whole output frame spooled. Resume restores the
    frame without recomputing; a deterministic failure quarantines the
    one block (``completed`` is then ``None``)."""
    from . import ops as _ops
    from ..frame import TensorFrame

    frame = grouped.frame
    n = frame.num_rows
    # capture (memoized per callable) just for the fingerprint: resume
    # with a different program must fail loudly, same as the other ops
    g = _ops._as_graph(fetches, frame, cell_inputs=False)
    ledger.ensure_plan(
        [{"rows": n, "first": 0, "last": max(n - 1, 0)}],
        graph=g,
        schema=frame.schema,
        rows=n,
        extra={"keys": list(grouped.keys)},
    )
    st, arrs = ledger.lookup(0)
    if st == "quarantined":
        return None
    if st == "todo":
        def compute():
            out = _ops.aggregate(fetches, grouped).cache()
            spool: Dict[str, np.ndarray] = {}
            for name in out.columns:
                cd = out.column_data(name)
                if cd.is_binary or cd.dense is None:
                    cells = np.empty(cd.num_rows, dtype=object)
                    cells[:] = list(cd.iter_cells())
                    spool[name] = cells
                else:
                    spool[name] = np.asarray(cd.host())
            return spool

        arrs = ledger.run_block(0, compute, rows=n)
        if arrs is None:
            return None
    cols = {
        name: (list(arr) if arr.dtype == object else arr)
        for name, arr in arrs.items()
    }
    return TensorFrame.from_columns(cols).analyze()


def _drive(
    ledger: BlockLedger,
    fetches,
    data,
    *,
    strict: bool,
    trim: bool,
    feed_dict,
    constants,
    resumed: bool,
) -> JobResult:
    # the job's trace identity: adopted from the journal on resume (the
    # manifest carries it), inherited from the caller's ambient trace on
    # a fresh job, minted otherwise — the manifest is stamped either
    # way, so every later worker/resume continues ONE trace
    if ledger._trace is None:
        ledger._trace = _current_trace() or _new_trace()
    _register_start(ledger, resumed)
    ok = False
    try:
        with _use_trace(ledger._trace), _span(
            "jobs.run", job=ledger.job_id, op=ledger.op, resumed=resumed
        ):
            completed = _execute(
                ledger.op, fetches, data, ledger, trim, feed_dict, constants
            )
        ledger.finalize()
        ok = True
    finally:
        if not ok:
            ledger.abort()  # keep journaled state; don't mask the error
        _register_end(ledger, ok)
    result = JobResult(
        job_id=ledger.job_id,
        op=ledger.op,
        path=ledger.path,
        completed=completed,
        quarantined=ledger.quarantined,
        resumed=resumed,
        blocks_total=ledger.num_blocks,
        blocks_computed=ledger.computed,
        blocks_restored=ledger.restored,
    )
    if strict:
        result.raise_if_quarantined()
    return result


def run_job(
    op: str,
    fetches,
    data,
    *,
    job_dir: Optional[str] = None,
    job_id: Optional[str] = None,
    journal: Optional[bool] = None,
    strict: Optional[bool] = None,
    trim: bool = False,
    feed_dict: Optional[Dict[str, str]] = None,
    constants: Optional[Dict[str, Any]] = None,
) -> JobResult:
    """Run a batch op as a durable job.

    ``op`` is one of ``map_rows`` / ``map_blocks`` / ``reduce_blocks``
    (``data`` is a :class:`~tensorframes_tpu.frame.TensorFrame`) or
    ``aggregate`` (``data`` is a
    :class:`~tensorframes_tpu.frame.GroupedFrame`). Execution is
    *eager* — durability means doing the work now, not promising it.

    ``journal`` (default ``Config.journal_batch_jobs``) controls the
    on-disk journal under ``job_dir or Config.job_dir``; ``False`` keeps
    the deterministic block loop + quarantine semantics with no disk
    I/O. ``strict`` (default ``not Config.quarantine_blocks``) raises
    :class:`~tensorframes_tpu.utils.failures.QuarantinedBlocksError` at
    job end instead of returning a partial :class:`JobResult` — healthy
    blocks still complete and journal first, so a later
    ``resume_job(retry_quarantined=True)`` only re-attempts the poison.

    ``op="pipeline"`` journals a whole **fused logical plan**
    (``engine/plan.py``, docs/pipelines.md): ``data`` is a pending lazy
    planned frame (a chain of map ops, optionally trailed by
    select/filter), ``fetches`` must be ``None``. The chain lowers to
    ONE engine op with a deterministic composite program, so the
    pipeline canonicalizes to one manifest fingerprint — it journals,
    resumes, and distributes exactly like a single op, and trailing
    select/filter nodes replay on the assembled result.
    """
    from ..utils import get_config

    cfg = get_config()
    post = None
    if op == "pipeline":
        if fetches is not None:
            raise ValueError(
                "run_job('pipeline', ...) derives the program from the "
                "planned frame; pass fetches=None"
            )
        from . import plan as _plan_mod

        op, fetches, data, consts, post = _plan_mod.lower_for_job(data)
        if constants is None:
            constants = consts
    if journal is None:
        journal = cfg.journal_batch_jobs
    if strict is None:
        strict = not cfg.quarantine_blocks
    if op not in _OPS:
        raise ValueError(f"unknown job op {op!r}; expected one of {_OPS}")
    job_id = job_id or _new_job_id(op)
    path = None
    if journal:
        root = job_dir or cfg.job_dir or _default_job_dir()
        path = os.path.join(root, job_id)
    ledger = BlockLedger.create(path, job_id, op)
    result = _drive(
        ledger, fetches, data, strict=strict, trim=trim,
        feed_dict=feed_dict, constants=constants, resumed=False,
    )
    if post is not None:
        result.completed = post(result.completed)
    return result


def resume_job(
    path: str,
    fetches,
    data,
    *,
    strict: Optional[bool] = None,
    trim: bool = False,
    feed_dict: Optional[Dict[str, str]] = None,
    constants: Optional[Dict[str, Any]] = None,
    retry_quarantined: bool = False,
) -> JobResult:
    """Resume a journaled job from its directory.

    The caller supplies the same ``fetches`` and input ``data`` the
    original run had (journals spool *results*, not inputs — the input
    frame is the caller's durable artifact, as it was Spark's); the
    manifest fingerprint and block plan are validated against them.
    Completed blocks restore from their spools; only unfinished blocks
    recompute, and the final output is byte-identical to a clean run.
    ``retry_quarantined=True`` clears quarantine records first so
    poisoned blocks re-attempt (after an upstream fix).

    A resume takes the **journal-level lease** for its duration and
    refuses (:class:`~tensorframes_tpu.utils.failures.StaleLeaseError`)
    while distributed workers hold live block leases on this journal —
    in particular, ``retry_quarantined=True`` clearing
    ``quarantine.json`` under an active drain would race the live job.
    Use :func:`~tensorframes_tpu.engine.dist_jobs.wait_job` to assemble
    a distributed job's result instead.

    A journaled **pipeline** (``run_job("pipeline", ...)``) resumes the
    same way: pass ``fetches=None`` and the same pending planned frame
    as ``data`` — the chain re-lowers to the identical composite
    program (one canonical fingerprint) and trailing select/filter
    nodes replay on the assembled result."""
    from .dist_jobs import journal_guard

    post = None
    if fetches is None and getattr(data, "_plan_node", None) is not None:
        from . import plan as _plan_mod

        _kind, fetches, data, consts, post = _plan_mod.lower_for_job(data)
        if constants is None:
            constants = consts
    with journal_guard(path, what="resume_job"):
        ledger = BlockLedger.open_(path)
        if retry_quarantined:
            ledger.clear_quarantine()
        _m_resumes.inc()
        if strict is None:
            from ..utils import get_config

            strict = not get_config().quarantine_blocks
        result = _drive(
            ledger, fetches, data, strict=strict, trim=trim,
            feed_dict=feed_dict, constants=constants, resumed=True,
        )
    if post is not None:
        result.completed = post(result.completed)
    return result
