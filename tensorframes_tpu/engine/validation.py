"""Pre-flight validation: the schema contract every op enforces.

Analog of the reference's ``SchemaTransforms``
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala:53-275``)
and its error types (``Operations.scala:7-15``):

- every graph input must be fed by a frame column or a constant
  (``InputNotFoundException``);
- **no implicit casting** — placeholder dtype must equal column dtype
  (``core.py:236-237``);
- placeholder shapes must be compatible with the column's (analyzed) shape,
  with ``Unknown`` acting as a wildcard (``Shape.checkMorePreciseThan``,
  ``Shape.scala:54-59``);
- map outputs must not collide with existing column names
  (``Operations.scala:30-31``);
- reduce naming conventions: fetch ``x`` pairs with placeholder ``x_input``
  (block reduce, one dim higher) or ``x_1``/``x_2`` (row reduce, same shape)
  (``Operations.scala:83-108``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..capture.graph import CapturedGraph, TensorSpec
from ..schema import ColumnInfo, FrameInfo, Shape, Unknown

__all__ = [
    "InputNotFoundError",
    "InvalidTypeError",
    "InvalidDimensionError",
    "OutputCollisionError",
    "resolve_column",
    "validate_map_inputs",
    "validate_reduce_block_graph",
    "validate_reduce_row_graph",
    "check_output_collisions",
]

#: suffixes that bind a placeholder to a column by convention
#: (reference ``Operations.scala:86-107``).
REDUCE_SUFFIXES = ("_input", "_1", "_2")


class InputNotFoundError(KeyError):
    """Analog of ``InputNotFoundException`` (``Operations.scala:7-8``)."""

    def __init__(self, inputs: Sequence[str], available: Sequence[str]):
        self.inputs = list(inputs)
        msg = (
            f"The following inputs were not provided: {', '.join(inputs)} "
            f"(available columns: {', '.join(available)})"
        )
        super().__init__(msg)
        self.msg = msg

    def __str__(self):
        return self.msg


class InvalidTypeError(TypeError):
    """No implicit casting (``Operations.scala:14-15``)."""


class InvalidDimensionError(ValueError):
    """Shape incompatibility (``Operations.scala:10-12``)."""


class OutputCollisionError(ValueError):
    """Fetch name equals an existing column (``Operations.scala:30-31``)."""


def resolve_column(
    ph_name: str,
    inputs_map: Dict[str, str],
    columns: Sequence[str],
    allow_suffix: bool = True,
) -> Optional[str]:
    """Find the frame column feeding a placeholder: explicit map first, then
    the placeholder's own name, then reduce-convention suffix stripping."""
    col = inputs_map.get(ph_name, ph_name)
    if col in columns:
        return col
    if allow_suffix:
        for suf in REDUCE_SUFFIXES:
            if col.endswith(suf) and col[: -len(suf)] in columns:
                return col[: -len(suf)]
    return None


def _compatible(declared: Shape, actual: Shape) -> bool:
    """Shapes agree wherever both are known (Unknown = wildcard)."""
    if declared.num_dims != actual.num_dims:
        return False
    return all(
        a == Unknown or b == Unknown or a == b
        for a, b in zip(declared.dims, actual.dims)
    )


def validate_map_inputs(
    graph: CapturedGraph,
    schema: FrameInfo,
    block: bool,
    constants: Optional[set] = None,
) -> Dict[str, str]:
    """Check every placeholder maps to a column with matching dtype and a
    compatible shape; returns placeholder name -> column name.

    ``block=True``: placeholder shape is a block shape (one dim higher than
    the cell, ``Operations.scala:52-53``); ``block=False``: cell shape.
    Placeholders named in ``constants`` are fed per call, not from columns,
    and are skipped here."""
    binding: Dict[str, str] = {}
    missing: List[str] = []
    for ph in graph.placeholders.values():
        if constants and ph.name in constants:
            continue
        col = resolve_column(ph.name, graph.inputs_map, schema.names)
        if col is None:
            missing.append(ph.name)
            continue
        binding[ph.name] = col
    if missing:
        raise InputNotFoundError(missing, schema.names)
    for ph_name, col_name in binding.items():
        ph = graph.placeholders[ph_name]
        info = schema[col_name]
        if ph.scalar_type.name != info.scalar_type.name:
            raise InvalidTypeError(
                f"Input {col_name!r} is of type {info.scalar_type.name}, but "
                f"the graph expected an input of type {ph.scalar_type.name} "
                f"for placeholder {ph_name!r} (no implicit casting is "
                f"performed)"
            )
        expected = info.block_shape if block else info.cell_shape
        if not _compatible(ph.shape, expected):
            kind = "block" if block else "cell"
            raise InvalidDimensionError(
                f"Placeholder {ph_name!r} declares shape {ph.shape}, which is "
                f"incompatible with column {col_name!r}'s {kind} shape "
                f"{expected}"
            )
    return binding


def check_output_collisions(
    out_specs: Dict[str, TensorSpec], schema: FrameInfo
) -> None:
    for name in out_specs:
        if name in schema:
            raise OutputCollisionError(
                f"Output {name!r} has the same name as an existing column; "
                f"map outputs must be all different from the names of "
                f"existing columns"
            )


def validate_reduce_block_graph(
    graph: CapturedGraph, schema: FrameInfo
) -> Dict[str, str]:
    """For each fetch ``x``: require placeholder ``x_input`` whose dtype
    equals the column's, with shape one dim higher than the cell
    (reference ``reduceBlocksSchema``, ``DebugRowOps.scala:80-170``).
    Returns fetch name -> column name."""
    binding: Dict[str, str] = {}
    missing: List[str] = []
    for fetch in graph.fetch_names:
        ph_name = f"{fetch}_input"
        if ph_name not in graph.placeholders:
            raise InvalidDimensionError(
                f"Reduce fetch {fetch!r} requires a placeholder named "
                f"{ph_name!r} (block-reduce naming convention); placeholders: "
                f"{sorted(graph.placeholders)}"
            )
        col = resolve_column(ph_name, graph.inputs_map, schema.names)
        if col is None:
            missing.append(ph_name)
            continue
        binding[fetch] = col
    if missing:
        raise InputNotFoundError(missing, schema.names)
    for fetch, col in binding.items():
        ph = graph.placeholders[f"{fetch}_input"]
        info = schema[col]
        if ph.scalar_type.name != info.scalar_type.name:
            raise InvalidTypeError(
                f"Column {col!r} is {info.scalar_type.name} but placeholder "
                f"{fetch}_input expects {ph.scalar_type.name}"
            )
        if ph.shape.num_dims != info.cell_shape.num_dims + 1:
            raise InvalidDimensionError(
                f"Block-reduce placeholder {fetch}_input must be one "
                f"dimension higher than column {col!r}: placeholder "
                f"{ph.shape} vs cell {info.cell_shape}"
            )
        if not _compatible(ph.shape.tail(), info.cell_shape):
            raise InvalidDimensionError(
                f"Block-reduce placeholder {fetch}_input shape {ph.shape} is "
                f"incompatible with column {col!r} cell shape {info.cell_shape}"
            )
    return binding


def validate_reduce_row_graph(
    graph: CapturedGraph, schema: FrameInfo
) -> Dict[str, str]:
    """For each fetch ``x``: require placeholders ``x_1`` and ``x_2`` with the
    column's dtype and cell shape (reference ``reduceRowsSchema``,
    ``DebugRowOps.scala:172-275``). Returns fetch name -> column name."""
    binding: Dict[str, str] = {}
    missing: List[str] = []
    for fetch in graph.fetch_names:
        for suffix in ("_1", "_2"):
            ph_name = f"{fetch}{suffix}"
            if ph_name not in graph.placeholders:
                raise InvalidDimensionError(
                    f"Row-reduce fetch {fetch!r} requires placeholders "
                    f"{fetch}_1 and {fetch}_2; placeholders: "
                    f"{sorted(graph.placeholders)}"
                )
        col = resolve_column(f"{fetch}_1", graph.inputs_map, schema.names)
        if col is None:
            missing.append(f"{fetch}_1")
            continue
        binding[fetch] = col
    if missing:
        raise InputNotFoundError(missing, schema.names)
    for fetch, col in binding.items():
        info = schema[col]
        for suffix in ("_1", "_2"):
            ph = graph.placeholders[f"{fetch}{suffix}"]
            if ph.scalar_type.name != info.scalar_type.name:
                raise InvalidTypeError(
                    f"Column {col!r} is {info.scalar_type.name} but "
                    f"placeholder {fetch}{suffix} expects {ph.scalar_type.name}"
                )
            if not _compatible(ph.shape, info.cell_shape):
                raise InvalidDimensionError(
                    f"Row-reduce placeholder {fetch}{suffix} shape {ph.shape} "
                    f"is incompatible with column {col!r} cell shape "
                    f"{info.cell_shape}"
                )
    return binding
