"""Distributed journaled jobs: K crash-prone workers drain one manifest.

``engine/jobs.py`` made a batch job durable against the death of *its
one process*; this module makes the job survive — and scale across —
**many** processes. The ``BlockLedger``'s deterministic block plan is
already a durable work queue (every block is an independent, pure,
byte-reproducible unit — the RDD/Spark property the reference leaned
on); what was missing is the coordination letting independent workers
drain it safely with **no coordinator**: the journal directory itself
is the source of truth, exactly as it already is for crash-resume.

Mechanics (all under ``<job>/leases/``):

- **atomic block leasing** — a worker claims block ``i`` by atomically
  creating ``block-{i:05d}.e{epoch:06d}.lease`` (hard-link of a fully
  written temp file — create-if-absent AND complete content in one
  atomic step) carrying ``{worker_id, epoch, deadline_unix}``. The
  *epoch is part of the filename*, so claiming a given (block, epoch)
  has exactly one winner with no lock server.
- **heartbeats + expiry** — a background thread renews every owned
  lease (atomic rewrite of the epoch file with a fresh deadline) every
  ``heartbeat_s``; a lease whose deadline passed is presumed dead and
  any worker may **reclaim** the block by creating the ``epoch + 1``
  file — again exactly one winner — and recomputing it (byte-identical:
  it is literally the resume path).
- **write fencing** — every spool write and ledger append carries the
  writer's ``(worker_id, epoch)`` and re-validates the lease *inside*
  the journal writer immediately before mutating: a zombie worker that
  wakes after its lease was stolen holds a stale epoch, its late write
  raises :class:`~tensorframes_tpu.utils.failures.StaleLeaseError`
  (``jobs.fence_rejects_total``), and — belt and braces — replay
  ignores any done-record superseded by a higher epoch. No torn or
  duplicate block record ever lands. (The residual check-then-rename
  window is harmless by construction: blocks are deterministic, so even
  a write that slipped the fence carries byte-identical content and
  loses the replay arbitration.)
- **terminal markers** — a recorded block's lease file is rewritten to
  ``state="done"`` instead of unlinked, so a worker whose in-memory
  journal snapshot predates the record skips the block at claim time
  instead of wastefully (and duplicate-recordingly) recomputing it.
  Quarantine releases the lease instead (a later
  ``retry_quarantined`` drain must be able to re-claim the block).

A worker is one call — ``run_worker(op, fetches, data, path=...)`` —
and drains in **passes**: each pass re-reads the journal, claims every
block still unclaimed (or reclaims expired ones) as the engine's block
loop reaches it, computes and records them, and skips everything owned
elsewhere; between fruitless passes it polls. Workers need no network,
no ranks, no membership — start K of them whenever, kill any of them
wherever, add more mid-job. Any process (a worker or none of them)
assembles the final :class:`~tensorframes_tpu.engine.jobs.JobResult`
with :func:`wait_job`, which waits for every block to reach a terminal
state and then runs the ordinary resume path (all blocks restore from
their spools; quarantine/strict/torn-tail semantics are therefore
*identical* to the single-worker journal).

Liveness vs safety knobs: ``lease_ttl_s`` (how long a dead worker's
block stays stuck before reclamation — and how long a *live* worker's
heartbeats may stall before it is presumed dead and fenced) and
``heartbeat_s`` (renewal cadence, default ``ttl / 3``). Leases compare
``deadline_unix`` against the local clock, so the TTL must comfortably
exceed heartbeat jitter + filesystem latency + inter-worker clock
skew. The per-block retry window is clipped below the TTL
(:class:`~tensorframes_tpu.utils.failures.retry_deadline`) so a
retrying-but-alive worker gives up before it is presumed dead.

Chaos sites: ``jobs.lease`` (claim/reclaim path) and
``jobs.heartbeat`` (renewal — ``latency`` past the TTL is the
presumed-dead drill). See docs/fault_tolerance.md for the cookbook and
the multi-process kill soak in ``tests/test_dist_jobs.py``.

The lease *mechanics* (epoch-stamped files, atomic claim, heartbeats,
ownership re-validation) are the reusable primitive
:class:`~tensorframes_tpu.utils.leases.LeaseStore` — the serving
fleet's member registry (:mod:`tensorframes_tpu.serve.membership`)
runs on the same machinery. This module keeps the *job policy*:
block/journal keys, the guard/worker handshake, ``jobs.*`` metrics,
chaos sites, and the journal-writer write fence.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import socket
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..obs import (
    event as _trace_event,
    flight as _flight,
    new_trace as _new_trace,
    span as _span,
    use_trace as _use_trace,
)
from ..obs.metrics import counter as _counter, gauge as _gauge
from ..utils import get_logger
from ..utils.failures import (
    StaleLeaseError,
    retry_deadline,
    run_with_retries,
)
from ..utils.leases import LeaseStore, LeaseView
from .jobs import (
    _BLOCK_DIR,
    _OPS,
    BlockLedger,
    JobResult,
    _execute,
    _m_fence_rejects,
    _register_end,
    _register_start,
)

__all__ = [
    "LeaseManager",
    "LeaseView",
    "WorkerReport",
    "journal_guard",
    "journal_status",
    "run_worker",
    "wait_job",
]

logger = get_logger("dist_jobs")

_LEASE_DIR = "leases"
_JOURNAL_KEY = "journal"

_m_claims = _counter(
    "jobs.leases_claimed_total",
    "Distributed-job block leases claimed fresh (epoch 0 or re-claim "
    "of a released block)",
)
_m_reclaims = _counter(
    "jobs.leases_reclaimed_total",
    "Distributed-job block leases reclaimed from a presumed-dead "
    "worker (expired deadline; epoch bumped, block recomputed)",
)
_m_heartbeats = _counter(
    "jobs.lease_heartbeats_total",
    "Lease heartbeat renewals across all distributed-job workers",
)
_g_leases_held = _gauge(
    "jobs.leases_held",
    "Block leases currently held, per distributed-job worker",
    labels=("worker",),
)
_g_worker_blocks = _gauge(
    "jobs.worker_blocks_recorded",
    "Blocks durably recorded by this process, per distributed-job "
    "worker identity",
    labels=("worker",),
)


def _block_key(block: Optional[int]) -> str:
    return _JOURNAL_KEY if block is None else f"block-{block:05d}"


class LeaseManager(LeaseStore):
    """Filesystem lease table for one journal directory.

    The mechanics — epoch-stamped ``<key>.e{epoch:06d}.lease`` files,
    atomic exclusive claims, heartbeat renewal with ownership
    re-validation — are inherited from
    :class:`~tensorframes_tpu.utils.leases.LeaseStore`; see its
    docstring for why epoch-in-the-filename makes every (key, epoch)
    claim single-winner with no lock server. This subclass adds the
    *job* policy: block vs journal keys, the resume-guard handshake,
    ``jobs.*`` metrics + chaos sites, and the journal writer's
    :meth:`fence_check`."""

    def __init__(
        self,
        path: str,
        worker_id: str,
        ttl_s: float,
        heartbeat_s: float = 0.0,
        create: bool = True,
    ):
        super().__init__(
            path, worker_id, ttl_s, heartbeat_s=heartbeat_s, create=create
        )
        self.claimed_total = 0
        self.reclaimed_total = 0

    # -- scanning ----------------------------------------------------------

    def live_block_leases(self) -> List[LeaseView]:
        """Live (unexpired, not done, not ours) block leases — the
        "someone is actively draining this journal" signal the resume
        guard refuses on."""
        return [
            v
            for v in self.scan_all()
            if v.key != _JOURNAL_KEY
            and v.state != "done"
            and not v.expired
            and v.worker != self.worker_id
        ]

    def journal_locked(self) -> bool:
        """A live journal-level lease held by someone else — a resume or
        assembly owns the journal; block claims must stand down."""
        cur = self._scan(_JOURNAL_KEY)
        return (
            cur is not None
            and cur.state != "done"
            and not cur.expired
            and cur.worker != self.worker_id
        )

    # -- claiming ----------------------------------------------------------

    def try_acquire(self, block: Optional[int]) -> Optional[int]:
        """Claim (or reclaim) one block's lease; ``None`` is the
        journal-level lease. Returns the held epoch, or ``None`` when
        the block is terminal, live-leased elsewhere, or the claim race
        was lost. Transient filesystem failures retry
        (``run_with_retries``); the chaos site ``jobs.lease`` sits
        inside the window."""
        from ..utils import chaos as _chaos

        key = _block_key(block)

        def attempt() -> Optional[int]:
            _chaos.site("jobs.lease")
            now = time.time()
            with self._lock:
                held = self._held.get(key)
            cur = self._scan(key)
            if held is not None:
                if cur is not None and cur.epoch == held[0]:
                    return held[0]  # still ours (epoch files are exclusive)
                # superseded or deleted underneath us: we lost it (and
                # our old epoch file, if a heartbeat resurrected it, is
                # dead weight — drop it so it cannot linger as a
                # phantom stale lease)
                self._drop_held(key, held[0], held[1])
            if block is not None and self.journal_locked():
                return None  # a resume/assembly owns the journal
            if cur is None:
                epoch, reclaim = 0, False
            elif cur.state == "done":
                return None  # terminal: recorded by someone, never re-run
            elif cur.deadline_unix > now:
                return None  # live, someone else's
            else:
                epoch, reclaim = cur.epoch + 1, True
            fname = f"{key}.e{epoch:06d}.lease"
            if not self._create_excl(fname, self._payload(epoch)):
                return None  # lost the exclusive race for this epoch
            if block is not None and self.journal_locked():
                # the guard/worker handshake: the resume guard acquires
                # the journal lease FIRST and scans block leases second;
                # a claim re-checks the journal lease AFTER winning. So
                # either our block lease existed when the guard scanned
                # (it refuses), or we see its journal lease here (we
                # retreat) — no interleaving lets both proceed.
                try:
                    os.unlink(os.path.join(self.dir, fname))
                except OSError:
                    pass
                return None
            with self._lock:
                self._held[key] = (epoch, fname)
            self._ensure_heartbeat()
            if key != _JOURNAL_KEY:
                # a POINT event, written to the trace sink immediately:
                # the claim survives this worker's kill -9 — the record
                # that lets a post-mortem show claim -> reclaim ->
                # record as one trace across processes and epochs
                _trace_event(
                    "jobs.lease.claim",
                    block=block,
                    epoch=epoch,
                    worker=self.worker_id,
                    reclaim=reclaim,
                )
            if reclaim and key != _JOURNAL_KEY:
                _m_reclaims.inc()
                self.reclaimed_total += 1
                _flight.record(
                    "jobs", "lease_reclaim", block=key, epoch=epoch,
                    worker=self.worker_id, prev_worker=cur.worker,
                )
                logger.warning(
                    "worker %s reclaimed %s at epoch %d from presumed-dead "
                    "worker %s (lease expired %.1fs ago); recomputing",
                    self.worker_id, key, epoch, cur.worker,
                    now - cur.deadline_unix,
                )
                # housekeeping: the superseded epoch files are dead weight
                self._unlink_superseded(key, epoch)
            elif key != _JOURNAL_KEY:
                _m_claims.inc()
                self.claimed_total += 1
            _g_leases_held.set(len(self._held), worker=self.worker_id)
            return epoch

        return run_with_retries(attempt, what="jobs.lease claim")

    # -- renewal / release -------------------------------------------------

    def renew_all(self) -> int:
        """One heartbeat sweep (ownership-re-validating; inherited).
        The chaos site ``jobs.heartbeat`` sits inside — a ``latency``
        injection longer than the TTL is the presumed-dead drill (the
        sweep stalls, the lease expires, the block is reclaimed, and
        this worker's late write is fence-rejected)."""
        from ..utils import chaos as _chaos

        _chaos.site("jobs.heartbeat")
        renewed = super().renew_all()
        for _ in range(renewed):
            _m_heartbeats.inc()
        return renewed

    def _heartbeat_sweep(self) -> None:
        self.renew_all()

    def mark_done(self, block: int, epoch: int) -> None:
        """Terminal marker: the block's record landed; rewrite the lease
        as ``state="done"`` so no stale-snapshot worker ever re-claims
        (and wastefully re-records) it."""
        key = _block_key(block)
        with self._lock:
            held = self._held.pop(key, None)
            if held is not None:
                self._rewrite(held[1], self._payload(epoch, state="done"))
        _g_leases_held.set(len(self._held), worker=self.worker_id)

    def release(self, block: Optional[int]) -> None:
        """Drop a lease and unlink its file (quarantine records and the
        journal-level lease: the key must become claimable again)."""
        key = _block_key(block)
        with self._lock:
            held = self._held.pop(key, None)
            if held is not None:
                try:
                    os.unlink(os.path.join(self.dir, held[1]))
                except OSError:
                    pass
        _g_leases_held.set(len(self._held), worker=self.worker_id)

    def fence_check(self, block: int, epoch: int) -> None:
        """The write fence: raise unless this worker still owns block
        ``block`` at exactly ``epoch`` — called inside the journal
        writer immediately before the spool rename + ledger append."""
        cur = self._scan(_block_key(block))
        if cur is None or cur.epoch != epoch or cur.worker != self.worker_id:
            _m_fence_rejects.inc()
            if cur is None:
                detail = "the lease file is gone"
            else:
                detail = (
                    f"superseded by epoch {cur.epoch} "
                    f"(worker {cur.worker!r}, state {cur.state})"
                )
            _flight.record(
                "fences", "fence_reject", block=block, epoch=epoch,
                worker=self.worker_id, detail=detail,
            )
            _flight.dump_bundle(
                "fence_reject",
                debounce_key=f"{block}",
                series_prefix="jobs.",
                extra={
                    "block": block,
                    "epoch": epoch,
                    "worker": self.worker_id,
                    "detail": detail,
                },
            )
            raise StaleLeaseError(
                f"worker {self.worker_id}: block {block} lease at epoch "
                f"{epoch} is stale — {detail}; dropping the late write "
                f"(the owner's recompute is byte-identical)"
            )

    def stop(self, unlink_held: bool = True) -> None:
        """Stop heartbeats and (by default) release everything held so
        other workers need not wait out the TTL."""
        super().stop(unlink_held=unlink_held)
        _g_leases_held.set(0, worker=self.worker_id)


# ---------------------------------------------------------------------------
# the distributed ledger (one drain pass's view)
# ---------------------------------------------------------------------------


class _DistLedger(BlockLedger):
    """One worker's view of the shared journal for ONE drain pass.

    The engine's block loops drive it exactly like the single-process
    ledger; the difference is what ``lookup`` means: a block journaled
    or owned elsewhere is *skipped* (reported like a quarantined block
    so the pass's partial output assembles mechanically — drain-pass
    outputs are discarded; only :func:`wait_job`'s final resume pass
    assembles for real), and a todo block is computed only after its
    lease is won. Records are stamped and fenced with this worker's
    ``(worker_id, epoch)``."""

    def __init__(self, path: str, job_id: str, op: str):
        super().__init__(path, job_id, op)
        self._lm: Optional[LeaseManager] = None
        self._retry_deadline_s: Optional[float] = None
        self._skipped: set = set()
        self._owned: Dict[int, int] = {}
        self._progressed = False
        self._quar_at_open = 0

    def _bind(
        self,
        lm: LeaseManager,
        retry_deadline_s: Optional[float],
    ) -> None:
        self._lm = lm
        self._retry_deadline_s = retry_deadline_s
        self._quar_at_open = len(self._quar)

    # -- engine-facing -----------------------------------------------------

    # NOTE: ``peek`` deliberately inherits the base class's in-memory
    # form — it sits in the upload prefetchers' per-block hot loop, and
    # a lease-directory listing per peek would be O(blocks²) across a
    # pass. The cost is one speculative window-deep upload for a block
    # another worker claimed since our snapshot; the lookup that
    # follows still skips it.

    def lookup(self, i: int):
        if i in self._quar:
            return "quarantined", None
        if i in self._done or self.try_claim(i) is None:
            # journaled already, terminal elsewhere, or live-leased by
            # another worker: skip — report as quarantined so the
            # discarded pass output assembles without this block
            self._skipped.add(i)
            return "quarantined", None
        return "todo", None

    def try_claim(self, i: int) -> Optional[int]:
        if i in self._owned:
            return self._owned[i]
        epoch = self._lm.try_acquire(i)
        if epoch is not None:
            self._owned[i] = epoch
            self._progressed = True
        return epoch

    def run_block(self, i, compute, rows=None):
        def bounded():
            # clip the block's transient-retry budget below the lease
            # TTL: a worker mid-retry must give up (and let the pass
            # fail resumable) before it is presumed dead and fenced
            with retry_deadline(self._retry_deadline_s):
                return compute()

        return super().run_block(i, bounded, rows)

    # -- fencing hooks -----------------------------------------------------

    def _writer_tag(self, i: int) -> Dict[str, Any]:
        return {
            "worker": self._lm.worker_id,
            "epoch": self._owned.get(i, 0),
        }

    def _fence_check(self, i: int) -> None:
        epoch = self._owned.get(i)
        if epoch is None:
            _m_fence_rejects.inc()
            _flight.record(
                "fences", "fence_reject", block=i,
                worker=self._lm.worker_id, detail="no lease held",
            )
            raise StaleLeaseError(
                f"worker {self._lm.worker_id}: no lease held for block "
                f"{i}; refusing the unfenced journal write"
            )
        self._lm.fence_check(i, epoch)

    def _on_recorded(self, i: int, done: bool = True) -> None:
        epoch = self._owned.pop(i, None)
        if done and epoch is not None:
            self._lm.mark_done(i, epoch)
        else:
            self._lm.release(i)
        _g_worker_blocks.inc(worker=self._lm.worker_id)

    def _spool_tmp_suffix(self) -> str:
        # concurrent workers share blocks/; tmp names must not collide
        return "." + "".join(
            c if c.isalnum() or c in "-_" else "-"
            for c in self._lm.worker_id
        )

    @property
    def quarantined_indices(self) -> List[int]:
        # the engine drops both truly-quarantined and skipped blocks'
        # rows from this pass's (discarded) output
        return sorted(set(self._quar) | self._skipped)

    @property
    def newly_quarantined(self) -> int:
        return max(0, len(self._quar) - self._quar_at_open)

    def finalize(self) -> None:
        # drain the writer, but write the completion marker only when
        # every plan block is actually terminal in THIS view — a drain
        # pass that skipped live-leased blocks must not declare the job
        # complete
        self._drain_writer()
        if self.path is not None and not self._complete and _terminal(self):
            super().finalize()
        elif self._ledger_file is not None and not self._ledger_file.closed:
            self._ledger_file.close()


# ---------------------------------------------------------------------------
# the worker
# ---------------------------------------------------------------------------


def _tuned_lease_ttl(default_s: float) -> float:
    """The autotuner's ``jobs.lease_ttl`` winner when one is stored
    (docs/tuning.md), else ``default_s`` (``Config.job_lease_ttl_s``).
    Cache-only on the drain path — there is no sane in-worker trial for
    a liveness/safety tradeoff; winners come from operator pins or the
    fleet's shared store. An explicit ``lease_ttl_s`` argument never
    reaches here (it always wins), and the TTL changes only WHEN a dead
    worker's blocks reclaim, never block results — the no-behavior-
    change contract every tuned surface carries."""
    try:
        from .. import tune

        if tune.mode() == "off":
            return default_s
        win = tune.lookup(
            "jobs.lease_ttl", tune.jobs_signature(),
            {"ttl_s": default_s},
        )
        ttl = float(win.get("ttl_s", default_s))
        return ttl if ttl > 0 else default_s
    except Exception:
        return default_s


def _default_worker_id() -> str:
    return (
        f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:4]}"
    )


def _terminal(led: BlockLedger) -> bool:
    """Every plan block with rows has reached a terminal state (done or
    quarantined). Empty blocks (0-row partitions) are never visited by
    the engine's block loops and count as terminal."""
    plan = led.stored_plan
    if plan is None:
        return False
    for i, entry in enumerate(plan):
        if int(entry.get("rows", 0) or 0) == 0:
            continue
        if i not in led._done and i not in led._quar:
            return False
    return True


def _attach(path: str, op: str) -> _DistLedger:
    """One pass's journal snapshot: open the manifest if it exists, a
    fresh ledger otherwise. The manifest-creation race between
    first-attaching workers is benign by construction — every worker
    derives the identical deterministic plan and fingerprint from the
    same inputs, `ensure_plan` validates both on the open_ path, and
    the write itself is an atomic rename."""
    try:
        led = _DistLedger.open_(path)
    except FileNotFoundError:
        os.makedirs(os.path.join(path, _BLOCK_DIR), exist_ok=True)
        led = _DistLedger(
            path, os.path.basename(os.path.normpath(path)), op
        )
    if led.op != op:
        raise ValueError(
            f"journal at {path!r} was written for op {led.op!r}; "
            f"this worker was started for {op!r}"
        )
    return led


@dataclasses.dataclass
class WorkerReport:
    """What one ``run_worker`` call did — serializable (``as_dict``) so
    multi-process harnesses can collect per-worker tallies."""

    worker_id: str
    path: str
    passes: int = 0
    blocks_computed: int = 0
    blocks_quarantined: int = 0
    leases_claimed: int = 0
    leases_reclaimed: int = 0
    fence_rejects: int = 0
    complete: bool = False

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def run_worker(
    op: str,
    fetches,
    data,
    *,
    path: str,
    worker_id: Optional[str] = None,
    lease_ttl_s: Optional[float] = None,
    heartbeat_s: Optional[float] = None,
    poll_s: float = 0.5,
    retry_deadline_frac: float = 0.8,
    transient_pass_retries: int = 3,
    max_idle_s: Optional[float] = None,
    trim: bool = False,
    feed_dict: Optional[Dict[str, str]] = None,
    constants: Optional[Dict[str, Any]] = None,
) -> WorkerReport:
    """Drain one journaled job as one of K independent workers.

    Every worker is started with the same ``op`` / ``fetches`` /
    ``data`` the job was defined with (inputs are the caller's durable
    artifact, exactly as for ``resume_job``) and the same journal
    ``path``; the first to attach writes the manifest, and from then on
    the filesystem coordinates everything — block leases, heartbeats,
    reclamation of presumed-dead workers' blocks, and write fencing of
    zombies. Workers may be started and killed at any time; adding one
    mid-job just drains the remaining blocks faster.

    Returns this worker's :class:`WorkerReport`; ``report.complete`` is
    True when the whole job (not just this worker's share) reached a
    terminal state. Assemble the job's :class:`JobResult` with
    :func:`wait_job` from any process.

    ``lease_ttl_s`` / ``heartbeat_s`` default to
    ``Config.job_lease_ttl_s`` / ``Config.job_heartbeat_s`` (0 meaning
    ``ttl / 3``). ``retry_deadline_frac`` clips each block's
    transient-retry budget to that fraction of the TTL
    (:class:`~tensorframes_tpu.utils.failures.retry_deadline`) so a
    retrying-but-alive worker is never presumed dead mid-retry.
    ``max_idle_s`` bounds how long the worker waits with nothing
    claimable before raising ``TimeoutError`` (default: wait forever —
    safety over liveness when another worker holds a block and is
    merely slow).

    A **transient** failure that escapes a pass (a ``jobs.block``-level
    fault, or a retry window that ran out) does not kill the worker
    outright: it re-scans and retries up to ``transient_pass_retries``
    consecutive fruitless times — a long-lived lease holder dying over
    one flaky dispatch would force pointless reclamation — and only
    then fails (resumable, like the single-process job). Fatal errors
    propagate immediately; blocks this worker had already recorded stay
    recorded either way.

    ``op="pipeline"``: drain a journaled **fused logical plan** — pass
    the same pending planned frame as ``data`` (``fetches=None``); the
    chain lowers to one composite op with a deterministic fingerprint,
    so K workers drain the fused pipeline exactly like a single op
    (``engine/plan.py``, docs/pipelines.md)."""
    from ..utils import get_config

    if op == "pipeline":
        from .plan import lower_for_job

        op, fetches, data, consts, _post = lower_for_job(data)
        if constants is None:
            constants = consts
    if op not in _OPS:
        raise ValueError(f"unknown job op {op!r}; expected one of {_OPS}")
    cfg = get_config()
    ttl = float(lease_ttl_s if lease_ttl_s is not None
                else _tuned_lease_ttl(cfg.job_lease_ttl_s))
    hb = float(heartbeat_s if heartbeat_s is not None
               else cfg.job_heartbeat_s)
    worker_id = worker_id or _default_worker_id()
    try:
        # fleet telemetry: stamp this process's identity gauge and let
        # the per-pass autoexport below publish it (obs/export.py); a
        # worker with no telemetry dir configured exports nothing
        from ..obs import export as _obs_export

        _obs_export.set_identity("job-worker")
    except Exception:
        logger.warning("worker telemetry identity failed", exc_info=True)
    lm = LeaseManager(path, worker_id, ttl, hb)
    jl = lm._scan(_JOURNAL_KEY)
    if jl is not None and not jl.expired and jl.worker != worker_id:
        raise StaleLeaseError(
            f"journal at {path!r} is held by {jl.worker!r} (a resume or "
            f"assembly is in progress); start workers after it releases "
            f"the journal lease"
        )
    report = WorkerReport(worker_id=worker_id, path=path)
    registered: Optional[BlockLedger] = None
    led: Optional[_DistLedger] = None
    idle_since: Optional[float] = None
    transient_budget = transient_pass_retries
    ok = False
    try:
        while True:
            led = _attach(path, op)
            if registered is None:
                _register_start(led, resumed=led.stored_plan is not None)
                registered = led
            if _terminal(led):
                report.complete = True
                ok = True
                break
            led._bind(lm, retry_deadline_s=ttl * retry_deadline_frac)
            if led._trace is None:
                # first worker on a journal with no manifest yet: mint
                # the job trace so ensure_plan stamps it; later passes
                # (and every other worker) adopt it from the manifest
                led._trace = _new_trace()
            try:
                with _use_trace(led._trace), _span(
                    "jobs.worker_pass", job=led.job_id, worker=worker_id
                ):
                    _execute(
                        op, fetches, data, led, trim, feed_dict, constants
                    )
                led.finalize()
            except StaleLeaseError as e:
                # our lease on some block was stolen mid-pass (we were
                # presumed dead); the write was fenced — drop the pass
                # and re-scan: the reclaimer's recompute is identical
                report.fence_rejects += 1
                logger.warning("worker %s: pass fenced: %s", worker_id, e)
                led.abort()
                # leases for blocks we still hold stay valid; the next
                # pass re-claims them from _held via try_acquire
                continue
            except Exception as e:
                led.abort()
                from ..utils.failures import is_transient

                if is_transient(e) and (
                    led.computed or transient_budget > 0
                ):
                    if not led.computed:
                        transient_budget -= 1
                    logger.warning(
                        "worker %s: pass failed transiently (%s); "
                        "re-scanning (%d fruitless retries left)",
                        worker_id, str(e).split("\n", 1)[0][:200],
                        transient_budget,
                    )
                    time.sleep(poll_s)
                    continue
                raise
            finally:
                report.passes += 1
                report.blocks_computed += led.computed
                report.blocks_quarantined += led.newly_quarantined
                try:
                    # piggyback telemetry export on the pass cadence so
                    # workers without a sampler thread still federate
                    # (throttled by Config.obs_export_interval_s)
                    from ..obs import export as _obs_export

                    _obs_export.autoexport()
                except Exception:
                    pass
            if led._progressed or led.computed:
                idle_since = None
                transient_budget = transient_pass_retries
                continue  # we did work; immediately look for more
            now = time.monotonic()
            idle_since = idle_since if idle_since is not None else now
            if max_idle_s is not None and now - idle_since > max_idle_s:
                raise TimeoutError(
                    f"worker {worker_id}: nothing claimable for "
                    f"{max_idle_s:.1f}s and the job is not terminal "
                    f"(blocks held live by other workers)"
                )
            time.sleep(poll_s)
    finally:
        report.leases_claimed = lm.claimed_total
        report.leases_reclaimed = lm.reclaimed_total
        lm.stop()
        if registered is not None:
            _register_end(led if led is not None else registered, ok)
        try:
            # final unthrottled snapshot: the worker's terminal counters
            # must reach the telemetry dir even if the last autoexport
            # was inside the throttle window
            from ..obs import export as _obs_export

            _obs_export.export_snapshot()
        except Exception:
            pass
    logger.info(
        "worker %s: job %s terminal after %d pass(es); computed %d "
        "block(s), reclaimed %d lease(s)",
        worker_id, led.job_id, report.passes, report.blocks_computed,
        report.leases_reclaimed,
    )
    return report


# ---------------------------------------------------------------------------
# assembly & introspection
# ---------------------------------------------------------------------------


def wait_job(
    path: str,
    fetches,
    data,
    *,
    timeout_s: Optional[float] = None,
    poll_s: float = 0.5,
    strict: Optional[bool] = None,
    trim: bool = False,
    feed_dict: Optional[Dict[str, str]] = None,
    constants: Optional[Dict[str, Any]] = None,
) -> JobResult:
    """Wait for a (distributed or not) journaled job to reach a
    terminal state, then assemble and return its
    :class:`~tensorframes_tpu.engine.jobs.JobResult`.

    Any process may call this — one of the workers, or none of them
    (the operator's laptop): assembly is the ordinary resume path, so
    every block restores from its spool, quarantine / strict-mode /
    torn-tail semantics are identical to the single-worker journal, and
    the result is byte-identical to a solo run no matter which workers
    computed which blocks. Raises ``TimeoutError`` after ``timeout_s``
    (default: wait forever)."""
    from .jobs import resume_job

    deadline = (
        time.monotonic() + timeout_s if timeout_s is not None else None
    )
    while True:
        led = None
        try:
            led = BlockLedger.open_(path)
        except FileNotFoundError:
            pass  # no manifest yet: the first worker hasn't attached
        if led is not None and _terminal(led):
            try:
                return resume_job(
                    path, fetches, data, strict=strict, trim=trim,
                    feed_dict=feed_dict, constants=constants,
                )
            except StaleLeaseError as e:
                # terminal journal but a live lease: a worker died (or
                # is about to exit) between recording its last block
                # and settling the lease file, or another assembly got
                # there first. Both clear on their own — keep polling
                # until the lease expires/releases or the timeout hits.
                logger.info(
                    "wait_job: journal terminal but not assemblable "
                    "yet (%s); polling", e,
                )
        if deadline is not None and time.monotonic() > deadline:
            done = len(led._done) if led is not None else 0
            total = led.num_blocks if led is not None else 0
            raise TimeoutError(
                f"job at {path!r} not terminal after {timeout_s:.1f}s "
                f"({done}/{total} blocks recorded)"
            )
        time.sleep(poll_s)


#: journal_status memo: path -> ((ledger mtime_ns, leases-dir
#: mtime_ns), ledger-derived static fields, raw LeaseViews). A health
#: probe re-reads the journal only when something actually changed —
#: every block record touches the ledger, every lease
#: claim/renewal/release touches the lease directory — so probes
#: against a finished (or idle) job cost two stat() calls, not a full
#: ledger replay per hit, forever. Only time-INDEPENDENT data is
#: cached: live-vs-expired is recomputed from the views' deadlines on
#: every call, because a lease EXPIRES without any filesystem change
#: (kill -9 the whole fleet and the stamp never moves — a cached
#: "live" would misreport a dead fleet as active forever).
_status_cache: Dict[
    str, Tuple[Tuple[int, int], Dict[str, Any], List[LeaseView]]
] = {}
_status_cache_lock = threading.Lock()


def _mtime_ns(p: str) -> int:
    try:
        return os.stat(p).st_mtime_ns
    except OSError:
        return -1


def journal_status(path: str) -> Dict[str, Any]:
    """Operator view of one journal directory, read from disk — block
    progress plus the distributed worker/lease table. This is what
    ``GET /healthz`` embeds (via ``jobs_status``) so ANY process's
    health endpoint shows the whole fleet draining the manifest, not
    just its own in-process registry. Memoized on the ledger's and
    lease directory's mtimes, so repeated probes against an unchanged
    journal are two ``stat()`` calls."""
    from .jobs import _LEDGER

    stamp = (
        _mtime_ns(os.path.join(path, _LEDGER)),
        _mtime_ns(os.path.join(path, _LEASE_DIR)),
    )
    with _status_cache_lock:
        hit = _status_cache.get(path)
    if hit is not None and hit[0] == stamp:
        static, views = hit[1], hit[2]
    else:
        try:
            led = BlockLedger.open_(path)
        except (FileNotFoundError, KeyError, ValueError):
            return {"path": path, "manifest": False}
        plan = led.stored_plan or []
        static = {
            "job_id": led.job_id,
            "op": led.op,
            "complete": led._complete,
            "terminal": _terminal(led),
            "blocks_total": led.num_blocks,
            "blocks_done": len(led._done),
            "blocks_quarantined": len(led.quarantined_indices),
            "blocks_empty": sum(
                1 for e in plan if int(e.get("rows", 0) or 0) == 0
            ),
        }
        views = LeaseManager(
            path, worker_id="status-probe", ttl_s=1.0, create=False
        ).scan_all()
        with _status_cache_lock:
            if len(_status_cache) > 8 and path not in _status_cache:
                _status_cache.pop(next(iter(_status_cache)))
            _status_cache[path] = (stamp, static, views)
    # live-vs-expired is classified NOW, from the cached deadlines — a
    # lease expires without any filesystem change, so this part must
    # never be served from the cache
    workers: Dict[str, Dict[str, Any]] = {}
    leased_live = 0
    journal_lease = None
    for v in views:
        if v.key == _JOURNAL_KEY:
            if v.state != "done" and not v.expired:
                journal_lease = {"worker": v.worker,
                                 "deadline_unix": v.deadline_unix}
            continue
        if v.state == "done":
            continue
        live = not v.expired
        leased_live += 1 if live else 0
        w = workers.setdefault(
            v.worker or "?",
            {"worker": v.worker or "?", "live_leases": 0,
             "stale_leases": 0, "next_deadline_unix": None},
        )
        if live:
            w["live_leases"] += 1
            nd = w["next_deadline_unix"]
            w["next_deadline_unix"] = (
                v.deadline_unix if nd is None else min(nd, v.deadline_unix)
            )
        else:
            w["stale_leases"] += 1
    return {
        "path": path,
        "manifest": True,
        "job_id": static["job_id"],
        "op": static["op"],
        "complete": static["complete"],
        "terminal": static["terminal"],
        "blocks": {
            "total": static["blocks_total"],
            "done": static["blocks_done"],
            "quarantined": static["blocks_quarantined"],
            "leased_live": leased_live,
            "empty": static["blocks_empty"],
        },
        "workers": sorted(
            workers.values(), key=lambda w: str(w["worker"])
        ),
        "journal_lease": journal_lease,
    }


@contextlib.contextmanager
def journal_guard(path: str, what: str = "resume_job"):
    """Journal-level mutual exclusion for single-process resume.

    Refuses (:class:`~tensorframes_tpu.utils.failures.StaleLeaseError`)
    when live block leases exist — a distributed drain is actively
    computing against this journal, and a resume (above all one
    clearing ``quarantine.json`` via ``retry_quarantined=True``) would
    race it — or when another process holds the journal-level lease
    (two concurrent resumes on one journal). Otherwise takes the
    journal lease, heartbeats it for the duration, and releases it on
    exit."""
    from ..utils import get_config

    lm = LeaseManager(
        path,
        worker_id=f"{what}-{_default_worker_id()}",
        ttl_s=get_config().job_lease_ttl_s,
    )
    # acquire the journal lease FIRST, scan block leases SECOND — the
    # other half of the claim-side handshake (try_acquire re-checks the
    # journal lease after winning a block): any worker claim either
    # already shows up in our scan below, or retreats when it sees our
    # journal lease. No interleaving lets a resume and a drain both
    # proceed.
    if lm.try_acquire(None) is None:
        cur = lm._scan(_JOURNAL_KEY)
        holder = cur.worker if cur is not None else "?"
        raise StaleLeaseError(
            f"{what}: journal at {path!r} is already locked by "
            f"{holder!r} (another resume or assembly is in progress)"
        )
    try:
        live = lm.live_block_leases()
        if live:
            holders = sorted({v.worker for v in live})
            raise StaleLeaseError(
                f"{what}: journal at {path!r} has {len(live)} live block "
                f"lease(s) held by worker(s) {holders}; a distributed "
                f"drain is active — assemble with wait_job(), or wait "
                f"for the leases to expire before resuming"
            )
        yield lm
    finally:
        lm.stop()
