"""Lazy logical plans: op fusion, column pruning, reduction hoisting.

The batch engine historically dispatched op-at-a-time: every ``map_rows``
/ ``map_blocks`` round-tripped through its own compiled program and (for
host-streamed data) its own transfer, so framework overhead plus data
movement dominated chip time on HBM-bound pipelines. Following Relay's
separation of a rewritable logical IR from lowering (PAPERS.md,
arXiv:1810.00952), chained frame ops now *record plan nodes* instead of
executing eagerly; the chain is optimized once when a fetch forces it
and lowered to the ordinary dispatch in ``engine/ops.py`` — OOM halving,
retries, chaos sites, and obs spans all intact.

Rewrite passes (each independently toggleable via ``Config``, each
byte-identity-tested against the unfused pipeline in tests/test_plan.py):

1. **map fusion** (``plan_fuse_maps``): a run of chained maps collapses
   into one jitted composite body — N logical ops, one compiled program,
   one pass over the data. Row maps fusing into a block-lowered group
   are lifted with ``jax.vmap`` (their per-row math is unchanged, so
   results stay byte-identical to the op-at-a-time chain).
2. **column pruning** (``plan_prune_columns``): liveness flows backward
   from the terminal demand (a ``select``'s projection, a reduce's
   bindings, an ``aggregate``'s bindings + keys); ops none of whose
   fetches are live are dropped, so the source columns only they bound
   are never uploaded — the ``frame.h2d_bytes_total`` delta is provable.
   (Dead fetches of partially-live ops are dropped from the composite's
   outputs too; XLA's DCE then removes their compute inside the body.)
3. **reduction hoisting** (``plan_hoist_reduce``): a ``reduce_blocks``
   terminal over a pending map chain folds into the map program's
   per-block epilogue — the fused partial program computes map outputs
   *and* the block partial in one dispatch, and partials still merge
   through the reduce graph's own ``[2, ...]`` program (the exact merge
   the unfused fold uses, so the fold is byte-identical).

Laziness semantics (docs/pipelines.md): recording is cheap — capture,
validation, and result-schema derivation still happen eagerly (errors
surface at call sites, schemas are available without forcing); only the
data work is deferred. Forcing a leaf executes its whole chain from the
source; intermediate frames stay lazy (forcing one later re-runs its own
prefix, byte-identically, with all compiled programs reused).
``select`` / ``filter_rows`` on a planned frame record nodes too —
``select`` is what gives the pruning pass its demand signal.

Journal interaction: a fused plan lowers to ONE engine op with a
deterministic composite graph, so it canonicalizes to one manifest
fingerprint — ``run_job("pipeline", None, lazy_frame)`` journals the
whole fused pipeline, resumes byte-identically across processes, and K
distributed workers (``run_worker``) drain it exactly like a single op.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..capture import CapturedGraph, TensorSpec
from ..frame import TensorFrame
from ..obs import span as _span
from ..obs.metrics import counter as _counter
from ..schema import ColumnInfo, FrameInfo, Unknown
from ..utils import get_logger

__all__ = [
    "PlanOp",
    "explain_plan",
    "lower_for_job",
    "make_lazy_map",
    "pruned_view",
    "record_filter",
    "record_select",
    "reduce_terminal",
]

logger = get_logger("plan")

_MAP_KINDS = ("map_rows", "map_blocks")

# -- plan telemetry (docs/observability.md) ---------------------------------
_m_passes = _counter(
    "plan.passes_total",
    "Logical-plan rewrite passes that fired (changed the plan), by pass",
    labels=("pass",),
)
_m_fused = _counter(
    "plan.fused_ops_total",
    "Logical ops absorbed into fused programs (map fusion absorbs the "
    "ops of each multi-op group; reduction hoisting absorbs the reduce)",
)
_m_pruned = _counter(
    "plan.pruned_columns_total",
    "Columns pruned by the column-pruning pass: dead fetches dropped "
    "from the plan plus source columns that never cross the link",
)


@dataclasses.dataclass
class PlanOp:
    """One recorded logical op. ``parent`` is the input frame (concrete,
    legacy-lazy, or itself planned — chains are walked through pending
    ``_plan_node`` links). Map nodes carry everything the eager prologue
    already derived (graph, binding, result schema) so lowering never
    re-validates; ``select`` / ``filter_rows`` nodes carry their
    projection / mask."""

    kind: str  # "map_rows" | "map_blocks" | "select" | "filter_rows"
    parent: TensorFrame
    result_info: FrameInfo
    graph: Optional[CapturedGraph] = None
    binding: Optional[Dict[str, str]] = None  # placeholder -> input column
    fetch_names: Tuple[str, ...] = ()
    constants: Optional[Dict[str, np.ndarray]] = None  # map_blocks only
    select_cols: Optional[Tuple[Tuple[str, str], ...]] = None  # (src, dst)
    filter_mask: Optional[np.ndarray] = None


def _cfg():
    from ..utils import get_config

    return get_config()


def _planned(frame) -> Optional[PlanOp]:
    """The frame's pending plan node, or None when the frame is concrete
    (already forced) or was built outside the plan layer."""
    node = getattr(frame, "_plan_node", None)
    if node is None or frame._thunk is None:
        return None
    return node


def _chain(leaf: PlanOp) -> Tuple[TensorFrame, List[PlanOp]]:
    """Walk pending plan links root-ward. Returns ``(source, ops)`` with
    ``ops`` in execution order; the walk stops at the first frame that is
    concrete or has no plan node (a forced intermediate acts as a
    materialized source — its prefix never recomputes)."""
    ops = [leaf]
    f = leaf.parent
    while True:
        node = _planned(f)
        if node is None:
            break
        ops.append(node)
        f = node.parent
    ops.reverse()
    return f, ops


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------


def enabled() -> bool:
    return bool(_cfg().plan_lazy_ops)


def make_lazy_map(
    kind: str,
    parent: TensorFrame,
    graph: CapturedGraph,
    binding: Dict[str, str],
    fetch_names: Sequence[str],
    result_info: FrameInfo,
    legacy_thunk: Callable[[], TensorFrame],
    constants: Optional[Dict[str, Any]] = None,
) -> TensorFrame:
    """Build the lazy result frame for a map op, carrying both a plan
    node (for chain optimization) and the op's own legacy thunk (the
    byte-identity reference path, used whenever no rewrite applies)."""
    node = PlanOp(
        kind=kind,
        parent=parent,
        result_info=result_info,
        graph=graph,
        binding=dict(binding),
        fetch_names=tuple(fetch_names),
        constants=(
            {k: np.asarray(v) for k, v in constants.items()}
            if constants
            else None
        ),
    )
    frame = TensorFrame(
        {},
        result_info,
        num_partitions=parent.num_partitions,
        _thunk=lambda: execute(node, legacy_thunk),
    )
    frame._plan_node = node
    return frame


def record_select(parent: TensorFrame, cols: Sequence) -> TensorFrame:
    """Lazy ``select`` on a planned frame: validates the projection
    against the (already known) schema without forcing, and records the
    node that gives the pruning pass its demand signal."""
    info = parent.schema
    pairs: List[Tuple[str, str]] = []
    new_infos: List[ColumnInfo] = []
    for c in cols:
        src, dst = (c, c) if isinstance(c, str) else c
        if src not in info:
            raise KeyError(f"No column {src!r}; columns: {info.names}")
        pairs.append((src, dst))
        new_infos.append(info[src].with_name(dst))
    result_info = FrameInfo(new_infos)
    node = PlanOp(
        kind="select",
        parent=parent,
        result_info=result_info,
        select_cols=tuple(pairs),
    )
    frame = TensorFrame(
        {},
        result_info,
        num_partitions=parent.num_partitions,
        _thunk=lambda: execute(node, None),
    )
    frame._plan_node = node
    return frame


def record_filter(parent: TensorFrame, mask) -> TensorFrame:
    """Lazy ``filter_rows`` on a planned frame. The mask is snapshotted
    (it is host data the caller could mutate before the force)."""
    node = PlanOp(
        kind="filter_rows",
        parent=parent,
        result_info=parent.schema,
        filter_mask=np.array(mask),
    )
    frame = TensorFrame(
        {},
        parent.schema,
        num_partitions=parent.num_partitions,
        _thunk=lambda: execute(node, None),
    )
    frame._plan_node = node
    return frame


# ---------------------------------------------------------------------------
# the optimizer: liveness (pruning) + grouping (fusion)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Stage:
    """One lowering stage after optimization: a fused group of ≥2 maps,
    a single map executed through its own legacy graph, or a post-op."""

    kind: str  # "fused" | "map" | "select" | "filter_rows"
    ops: List[PlanOp]
    group_kind: str = ""  # lowering kind for "fused" stages
    out_fetches: Tuple[str, ...] = ()


@dataclasses.dataclass
class _Optimized:
    stages: List[_Stage]
    fired: List[str]  # pass names that changed the plan
    dropped_ops: int
    dead_fetches: List[str]
    pruned_source_cols: List[str]
    fused_ops: int  # logical ops absorbed into fused stages
    source_needed: Optional[List[str]]  # None = no pruning applied


def _op_inputs(op: PlanOp) -> Set[str]:
    return set((op.binding or {}).values())


def _optimize(
    src: TensorFrame, ops: List[PlanOp], demand: Set[str], cfg
) -> _Optimized:
    """Run the rewrite pipeline over the chain (pure: no execution, no
    metrics — callers record what fired). ``demand`` is the set of
    column names the consumer of the leaf actually reads."""
    # -- pass: column pruning (liveness, leaf -> root) ----------------------
    live_ops: List[PlanOp] = []
    dead_fetches: List[str] = []
    dropped = 0
    needed = set(demand)
    below: Dict[int, Set[str]] = {}  # id(op) -> demand below that op
    for op in reversed(ops):
        below[id(op)] = set(needed)
        if op.kind == "select":
            # a select's demand is exactly the sources of its demanded
            # aliases; everything else stops here
            needed = {
                src_ for src_, dst in op.select_cols if dst in needed
            }
            live_ops.append(op)
            continue
        if op.kind == "filter_rows":
            live_ops.append(op)
            continue
        live = needed & set(op.fetch_names)
        if cfg.plan_prune_columns and not live:
            dropped += 1
            dead_fetches.extend(op.fetch_names)
            continue  # dead op: none of its outputs are ever read
        live_ops.append(op)
        needed = (needed - set(op.fetch_names)) | _op_inputs(op)
    live_ops.reverse()
    source_needed = sorted(needed & set(src.schema.names))
    pruned_source = (
        sorted(set(src.schema.names) - set(source_needed))
        if cfg.plan_prune_columns
        else []
    )
    prune_fired = bool(dropped or pruned_source)

    # -- pass: map fusion (maximal runs of map ops) -------------------------
    stages: List[_Stage] = []
    fused_ops = 0
    i = 0
    while i < len(live_ops):
        op = live_ops[i]
        if op.kind not in _MAP_KINDS or not cfg.plan_fuse_maps:
            stages.append(
                _Stage(
                    kind="map" if op.kind in _MAP_KINDS else op.kind,
                    ops=[op],
                )
            )
            i += 1
            continue
        j = i
        while j < len(live_ops) and live_ops[j].kind in _MAP_KINDS:
            j += 1
        group = live_ops[i:j]
        if len(group) == 1:
            stages.append(_Stage(kind="map", ops=group))
        else:
            gkind = (
                "map_blocks"
                if any(o.kind == "map_blocks" for o in group)
                else "map_rows"
            )
            # the group's outputs: group fetches still demanded BELOW
            # its last op (a fetch consumed only inside the group never
            # materializes — XLA DCEs its buffer). Without pruning, every
            # group fetch materializes, matching op-at-a-time carry.
            last = group[-1]
            out = set()
            for o in group:
                out |= set(o.fetch_names)
            if cfg.plan_prune_columns:
                out &= below[id(last)]
            stages.append(
                _Stage(
                    kind="fused",
                    ops=group,
                    group_kind=gkind,
                    out_fetches=tuple(sorted(out)),
                )
            )
            fused_ops += len(group)
        i = j
    fired = []
    if fused_ops:
        fired.append("fuse_maps")
    if prune_fired:
        fired.append("prune_columns")
    return _Optimized(
        stages=stages,
        fired=fired,
        dropped_ops=dropped,
        dead_fetches=sorted(dead_fetches),
        pruned_source_cols=pruned_source,
        fused_ops=fused_ops,
        source_needed=source_needed if cfg.plan_prune_columns else None,
    )


# ---------------------------------------------------------------------------
# composite graph construction
# ---------------------------------------------------------------------------


def _const_name(idx: int, ph: str) -> str:
    """Composite-level name for op ``idx``'s per-call constant ``ph``
    (two ops may use the same constant placeholder name)."""
    return f"__plan_c{idx}__{ph}"


#: bound on the per-graph composite memos below (FIFO eviction) — the
#: same discipline as ops.py's `_map_plan_cache`: a long-lived process
#: exploring many distinct chains off one shared first graph must not
#: accumulate composites (each closure pins its group's graphs) forever
_COMPOSE_CACHE_MAX = 64


def _compose_cache(host, attr: str) -> "OrderedDict":
    from collections import OrderedDict

    cache = getattr(host, attr, None)
    if cache is None:
        cache = OrderedDict()
        setattr(host, attr, cache)
    return cache


def _cache_put(cache: "OrderedDict", key, value) -> None:
    while len(cache) >= _COMPOSE_CACHE_MAX:
        cache.popitem(last=False)
    cache[key] = value


def _group_parts(
    group: List[PlanOp],
    schema: FrameInfo,
    block_shapes: bool,
    extra_cols: Sequence[str] = (),
):
    """The shared composite-construction pieces for a group of map ops:
    input placeholders (every source column some op — or ``extra_cols``
    — binds that no earlier op produces), renamed per-call constant
    specs, and the closure-safe ``steps`` tuples. ``steps`` deliberately
    captures only (kind, graph, binding, fetches, const names) — never
    the PlanOps, whose ``parent`` frames would otherwise be pinned by
    the graph-attached memo holding the composite."""
    from ..schema import Shape as _Shape
    from ..schema import for_numpy_dtype

    produced: Set[str] = set()
    in_cols: List[str] = []
    for op in group:
        for col in op.binding.values():
            if col not in produced and col not in in_cols:
                in_cols.append(col)
        produced |= set(op.fetch_names)
    for col in extra_cols:
        if col not in produced and col not in in_cols:
            in_cols.append(col)
    phs: List[TensorSpec] = []
    for col in in_cols:
        info = schema[col]
        shape = (
            info.block_shape.with_lead(Unknown)
            if block_shapes
            else info.cell_shape
        )
        phs.append(TensorSpec(col, info.scalar_type, shape))
    const_specs: List[TensorSpec] = []
    for idx, op in enumerate(group):
        for ph, arr in (op.constants or {}).items():
            const_specs.append(
                TensorSpec(
                    _const_name(idx, ph),
                    for_numpy_dtype(arr.dtype),
                    _Shape(arr.shape),
                )
            )
    steps = [
        (
            op.kind, op.graph, dict(op.binding),
            tuple(op.fetch_names), tuple(op.constants or ()),
        )
        for op in group
    ]
    return phs, const_specs, steps


def _const_feed_for(group: List[PlanOp]) -> Dict[str, np.ndarray]:
    return {
        _const_name(idx, ph): arr
        for idx, op in enumerate(group)
        for ph, arr in (op.constants or {}).items()
    }


def _run_steps(steps, feed: Dict[str, Any], vmap_row_ops: bool):
    """Trace the group's ops in order over ``feed``; returns the value
    environment (inputs + every op's fetches)."""
    import jax

    env = dict(feed)
    for idx, (kind, graph, binding, fetches, consts) in enumerate(steps):
        sub = {ph: env[col] for ph, col in binding.items()}
        for ph in consts:
            sub[ph] = feed[_const_name(idx, ph)]
        if vmap_row_ops and kind == "map_rows":
            # lift the row program over the block's lead axis; per-row
            # math (and therefore bytes) is unchanged
            out = jax.vmap(graph.fn)(sub)
        else:
            out = graph.fn(sub)
        for name in fetches:
            env[name] = out[name]
    return env


def _composite_for(
    stage: _Stage, schema: FrameInfo
) -> Tuple[CapturedGraph, Dict[str, np.ndarray]]:
    """Build (memoized) the fused CapturedGraph for a group of map ops.

    Placeholders are named after the source columns they bind (plus
    renamed per-call constants), so the engine's ordinary
    ``validate_map_inputs`` binds them with no feed_dict. Row-map ops
    inside a block-lowered group are lifted with ``jax.vmap``. The
    composite is memoized on the first op's graph keyed by the group's
    graph identities + output set, so repeated forces (and resumed
    journal jobs) reuse one compiled program."""
    group = stage.ops
    gkind = stage.group_kind
    out_fetches = stage.out_fetches
    key = (
        gkind,
        tuple(id(o.graph) for o in group),
        tuple(tuple(sorted(o.binding.items())) for o in group),
        out_fetches,
    )
    cache = _compose_cache(group[0].graph, "_plan_fuse_cache")
    composite = cache.get(key)
    if composite is None:
        phs, const_specs, steps = _group_parts(
            group, schema, block_shapes=(gkind == "map_blocks")
        )

        def fused_fn(feed: Dict[str, Any]) -> Dict[str, Any]:
            env = _run_steps(
                steps, feed, vmap_row_ops=(gkind == "map_blocks")
            )
            return {name: env[name] for name in out_fetches}

        composite = CapturedGraph(
            fused_fn, phs + const_specs, list(out_fetches)
        )
        #: the cost registry's display name (obs/programs.py): a fused
        #: composite should read as the fusion it is, not as an
        #: anonymous graph over its output columns
        composite.plan_label = (
            f"plan.fused:{gkind}[{len(group)} ops]:"
            + ",".join(out_fetches)
        )
        _cache_put(cache, key, composite)
    else:
        cache.move_to_end(key)
    return composite, _const_feed_for(group)


# ---------------------------------------------------------------------------
# lowering / execution
# ---------------------------------------------------------------------------


def _ops_mod():
    from . import ops as _ops

    return _ops


def _run_stage(stage: _Stage, cur: TensorFrame) -> TensorFrame:
    ops_mod = _ops_mod()
    if stage.kind == "select":
        return cur.select(*stage.ops[0].select_cols)
    if stage.kind == "filter_rows":
        return cur.filter_rows(stage.ops[0].filter_mask)
    if stage.kind == "map":
        op = stage.ops[0]
        if op.kind == "map_rows":
            return ops_mod.map_rows(op.graph, cur, _plan=False).cache()
        return ops_mod.map_blocks(
            op.graph, cur, constants=op.constants, _plan=False
        ).cache()
    # fused group
    if stage.group_kind == "map_blocks" and any(
        o.kind == "map_rows" for o in stage.ops
    ):
        # a row map lowered blockwise needs dense inputs; if any source
        # column feeding the group is ragged/binary, fall back to
        # op-at-a-time for this group (byte-identical, just unfused)
        for op in stage.ops:
            for col in op.binding.values():
                if col in cur.schema.names:
                    cd = cur.column_data(col)
                    if cd.dense is None:
                        for op2 in stage.ops:
                            cur = _run_stage(
                                _Stage(kind="map", ops=[op2]), cur
                            )
                        return cur
    composite, const_feed = _composite_for(stage, cur.schema)
    if stage.group_kind == "map_rows":
        return ops_mod.map_rows(composite, cur, _plan=False).cache()
    return ops_mod.map_blocks(
        composite, cur, constants=const_feed or None, _plan=False
    ).cache()


def _conform(frame: TensorFrame, result_info: FrameInfo) -> TensorFrame:
    """Reorder a materialized frame's columns to the leaf's declared
    schema (op-at-a-time nests fetches differently than one fused op;
    the bytes are identical, only the declared order must match)."""
    frame._force()
    cols = {c.name: frame._columns[c.name] for c in result_info}
    return TensorFrame(cols, result_info, offsets=frame._offsets)


def _record_metrics(opt: _Optimized) -> None:
    for p in opt.fired:
        _m_passes.inc(**{"pass": p})
    if opt.fused_ops:
        _m_fused.inc(opt.fused_ops)
    n_pruned = len(opt.dead_fetches) + len(opt.pruned_source_cols)
    if n_pruned and "prune_columns" in opt.fired:
        _m_pruned.inc(n_pruned)


def _lower(
    src: TensorFrame,
    ops: List[PlanOp],
    demand: Set[str],
    leaf: PlanOp,
    conform: bool = True,
) -> TensorFrame:
    cfg = _cfg()
    if not cfg.plan_lazy_ops:
        # a recorded chain forced AFTER the master switch went off (a
        # select/filter node has no legacy thunk to fall back to):
        # lower strictly op-at-a-time — no rewrites
        cfg = dataclasses.replace(
            cfg, plan_fuse_maps=False, plan_prune_columns=False
        )
    with _span("plan.optimize", ops=len(ops)) as sp:
        opt = _optimize(src, ops, demand, cfg)
        _record_metrics(opt)
        if sp is not None:
            sp.attrs["fired"] = ",".join(opt.fired) or "none"
            sp.attrs["stages"] = len(opt.stages)
    src._force()
    cur = src
    if opt.source_needed is not None and set(opt.source_needed) != set(
        src.schema.names
    ):
        # project the source down to what the plan actually reads: the
        # pruned columns are never bound, so they never cross the link,
        # and post-ops (filter's take) never touch them either
        keep = [c for c in src.schema.names if c in set(opt.source_needed)]
        cur = src.select(*keep)
    for stage in opt.stages:
        cur = _run_stage(stage, cur)
    cur._force()
    if conform and leaf.kind in _MAP_KINDS:
        return _conform(cur, leaf.result_info)
    return cur


def execute(
    node: PlanOp, legacy_thunk: Optional[Callable[[], TensorFrame]]
) -> TensorFrame:
    """Force a planned leaf: collect its chain, optimize, lower. With
    the plan layer disabled — or for a single map with nothing to
    rewrite — the op's own legacy thunk runs instead (the byte-identity
    reference path; zero behavior change vs the op-at-a-time engine)."""
    src, ops = _chain(node)
    if legacy_thunk is not None and (
        not enabled() or (len(ops) == 1 and node.kind in _MAP_KINDS)
    ):
        return legacy_thunk()
    if legacy_thunk is None and not ops:
        raise RuntimeError("select/filter plan node lost its chain")
    demand = {c.name for c in node.result_info}
    return _lower(src, ops, demand, node)


# ---------------------------------------------------------------------------
# pruned materialization for eager consumers (aggregate, unhoisted reduce)
# ---------------------------------------------------------------------------


_pruned_view_lock = threading.Lock()


def pruned_view(frame: TensorFrame, demand: Set[str]) -> TensorFrame:
    """Materialize a planned lazy frame *for an eager consumer that only
    reads ``demand``* — the chain executes with pruning driven by that
    demand, and ``frame`` itself STAYS lazy (forcing it later yields its
    full schema). Memoized per (demand, rewrite toggles) on the frame so
    repeated aggregates over one lazy pipeline execute it once."""
    node = _planned(frame)
    if node is None or not enabled():
        frame._force()
        return frame
    cfg = _cfg()
    key = (
        frozenset(demand),
        cfg.plan_fuse_maps,
        cfg.plan_prune_columns,
    )
    with _pruned_view_lock:
        cache = getattr(frame, "_plan_pruned_views", None)
        if cache is None:
            cache = frame._plan_pruned_views = {}
        hit = cache.get(key)
    if hit is not None:
        return hit
    src, ops = _chain(node)
    demand = set(demand) & {c.name for c in node.result_info}
    out = _lower(src, ops, set(demand), leaf=node, conform=False)
    # restrict to the demanded columns (pruned ones may be absent; the
    # consumer only reads `demand` by contract)
    present = [c for c in out.schema.names if c in demand]
    if set(present) != set(out.schema.names):
        out = out.select(*present)
    with _pruned_view_lock:
        cache[key] = out
    return out


# ---------------------------------------------------------------------------
# reduce_blocks terminal (reduction hoisting)
# ---------------------------------------------------------------------------


def _compose_reduce(
    map_stage: _Stage,
    gr: CapturedGraph,
    r_binding: Dict[str, str],
    schema: FrameInfo,
) -> Tuple[CapturedGraph, Dict[str, np.ndarray]]:
    """The hoisted partial program: per block, run the fused map body,
    then the reduce body on the mapped block — one dispatch per
    partition. Memoized like :func:`_composite_for` (on the reduce
    graph, keyed by the map group + binding)."""
    group = map_stage.ops
    key = (
        tuple(id(o.graph) for o in group),
        tuple(tuple(sorted(o.binding.items())) for o in group),
        tuple(sorted(r_binding.items())),
    )
    cache = _compose_cache(gr, "_plan_hoist_cache")
    composite = cache.get(key)
    if composite is None:
        # the reduce's own bindings are inputs too: a reduce may name a
        # source column the maps never touch
        phs, const_specs, steps = _group_parts(
            group, schema, block_shapes=True,
            extra_cols=list(r_binding.values()),
        )
        r_bind = dict(r_binding)

        def partial_fn(feed: Dict[str, Any]) -> Dict[str, Any]:
            env = _run_steps(steps, feed, vmap_row_ops=True)
            return gr.fn(
                {f"{f}_input": env[col] for f, col in r_bind.items()}
            )

        composite = CapturedGraph(
            partial_fn, phs + const_specs, list(gr.fetch_names)
        )
        composite.plan_label = (
            f"plan.hoisted_reduce[{len(group)} maps]:"
            + ",".join(gr.fetch_names)
        )
        _cache_put(cache, key, composite)
    else:
        cache.move_to_end(key)
    return composite, _const_feed_for(group)


def _lower_hoisted_reduce(
    src: TensorFrame,
    map_stage: _Stage,
    gr: CapturedGraph,
    r_binding: Dict[str, str],
    ledger=None,
):
    """Execute the hoisted reduce: one fused partial program per
    partition (retries / chaos / OOM halving intact), then the reduce
    graph's own ``[2, ...]`` merge folds the partials — the exact merge
    program the unfused path uses, so the fold is byte-identical.
    ``ledger`` spools per-partition partials for journaled jobs.

    This mirrors ``_reduce_blocks_impl``'s drive (grouped async dispatch
    unjournaled, per-partition sync + spool journaled, OOM degrade to
    halved spans merged through the reduce program) with the fused
    partial program in place of the raw reduce — a semantics change to
    either driver's retry/OOM/quarantine handling must be applied to
    BOTH (the reduce impl carries the matching cross-reference)."""
    import jax.numpy as jnp

    from ..utils import is_oom, run_with_retries
    from .ops import _block_feeder, _jitted

    ops_mod = _ops_mod()
    composite, const_feed = _compose_reduce(
        map_stage, gr, r_binding, src.schema
    )
    jit_part = _jitted(composite)
    merge_jit = None  # built lazily: a single partition never merges

    def merge_two(a, b):
        nonlocal merge_jit
        if merge_jit is None:
            merge_jit = _jitted(gr)
        feed = {
            f"{f}_input": jnp.stack([a[f], b[f]]) for f in gr.fetch_names
        }
        return merge_jit(feed)

    feeders = {}
    for col in composite.placeholders:
        if col in const_feed:
            continue
        src.column_block(col, None)  # rejects ragged/binary
        feeders[col], _ = _block_feeder(src.column_data(col))
    bounds = src.partition_bounds()

    def partial_for_span(lo: int, hi: int, what: str):
        feed = {col: fd(lo, hi) for col, fd in feeders.items()}
        feed.update(const_feed)

        def dispatch():
            import jax

            from ..utils.chaos import site as _chaos_site

            _chaos_site("engine.dispatch")
            return jax.block_until_ready(jit_part(feed))

        try:
            return run_with_retries(dispatch, what=what)
        except Exception as e:
            if is_oom(e):
                if hi - lo > 1:
                    from ..utils.failures import record_oom_split

                    record_oom_split("reduce_blocks")
                    logger.warning(
                        "hoisted reduce span of %d rows exhausted device "
                        "memory; halving and merging the halves", hi - lo,
                    )
                    del feed
                    mid = (lo + hi) // 2
                    a = partial_for_span(lo, mid, what)
                    b = partial_for_span(mid, hi, what)
                    return merge_two(a, b)
                from ..utils.failures import DeviceOOMError

                raise DeviceOOMError(
                    "hoisted reduce partial exhausted device memory even "
                    "at a single row"
                ) from e
            raise

    if ledger is not None:
        # journaled: per-partition dispatch with a sync each — host
        # partials must spool per block (failure isolation), exactly
        # like `_reduce_blocks_impl`'s ledger branch
        ledger.ensure_plan(
            [{"rows": hi - lo, "lo": lo, "hi": hi} for lo, hi in bounds],
            graph=composite, schema=src.schema, rows=src.num_rows,
            extra={"plan": "hoisted_reduce"},
        )
        partials = []
        for p, (lo, hi) in enumerate(bounds):
            if hi == lo:
                continue
            what = f"reduce_blocks partition {p}"
            st, arrs = ledger.lookup(p)
            if st == "quarantined":
                continue
            if st == "done":
                partials.append(arrs)
                continue
            res = ledger.run_block(
                p,
                lambda lo=lo, hi=hi, what=what: {
                    f: np.asarray(v)
                    for f, v in partial_for_span(lo, hi, what).items()
                },
                rows=hi - lo,
            )
            if res is not None:
                partials.append(res)
    else:
        # unjournaled: dispatch every partition async, ONE sync for the
        # group inside the retry window — the legacy reduce driver's
        # contract (per-partition syncing costs one host round-trip per
        # partition); an OOM inside the grouped dispatch falls back to
        # the sequential halving path above
        def feed_for(p):
            lo, hi = bounds[p]
            if hi == lo:
                return None
            f = {col: fd(lo, hi) for col, fd in feeders.items()}
            f.update(const_feed)
            return f

        def all_partials():
            import jax

            from ..utils.chaos import site as _chaos_site

            _chaos_site("engine.dispatch")
            ps = [
                jit_part(feed)
                for feed in map(feed_for, range(len(bounds)))
                if feed is not None
            ]
            return jax.block_until_ready(ps)

        try:
            partials = run_with_retries(
                all_partials, what="reduce_blocks partials"
            )
        except Exception as e:
            if not is_oom(e):
                raise
            logger.warning(
                "hoisted reduce grouped dispatch exhausted device "
                "memory; retrying per partition with OOM halving",
            )
            partials = [
                partial_for_span(lo, hi, f"reduce_blocks partition {p}")
                for p, (lo, hi) in enumerate(bounds)
                if hi > lo
            ]
    if not partials:
        if ledger is not None and ledger.quarantined_indices:
            return None
        raise ValueError("reduce_blocks on an empty frame")
    ops_mod._m_blocks.inc(len(partials), op="reduce_blocks")
    acc = partials[0]
    for part in partials[1:]:
        acc = merge_two(acc, part)
    return ops_mod._unpack_reduce_result(acc, gr.fetch_names)


def reduce_terminal(fetches, dframe: TensorFrame, ledger=None):
    """Plan-aware ``reduce_blocks``. Returns ``(handled, result,
    rows)``: ``handled=False`` means the chain did not qualify and the
    caller should run the legacy path (which forces the frame — fused
    maps still fire there, just without reduce-driven pruning).
    ``rows`` is the logical row count reduced, for the op metrics —
    computed without forcing the lazy leaf."""
    node = _planned(dframe)
    if node is None or not enabled():
        return False, None, None
    cfg = _cfg()
    ops_mod = _ops_mod()
    gr = ops_mod._as_graph(fetches, dframe, cell_inputs=False)
    from .validation import validate_reduce_block_graph

    r_binding = validate_reduce_block_graph(gr, dframe.schema)
    ops_mod._ensure_precision(gr, dframe.schema)
    src, ops = _chain(node)
    demand = set(r_binding.values())
    pure_maps = all(o.kind in _MAP_KINDS for o in ops)
    if cfg.plan_hoist_reduce and pure_maps:
        # optimize OUTSIDE any span: if the chain turns out not to be
        # hoistable this attempt is discarded and pruned_view/_lower
        # runs (and records, and emits the span for) the real
        # optimization — a span here would double-report one rewrite
        opt = _optimize(src, ops, demand, cfg)
        # hoistable: the surviving map chain collapsed to ONE stage
        # (one fused group, or a single map — fusion need not be on
        # for a 1-map chain); the reduce folds into its epilogue
        hoistable = len(opt.stages) == 1 and opt.stages[0].kind in (
            "fused",
            "map",
        )
        if hoistable:
            with _span("plan.optimize", ops=len(ops) + 1) as sp:
                stage = opt.stages[0]
                opt.fired.append("hoist_reduce")
                # absorbed ops = the maps in the hoisted program + the
                # reduce itself (replaces the map-fusion count: one
                # program now holds all of them)
                opt.fused_ops = len(stage.ops) + 1
                _record_metrics(opt)
                if sp is not None:
                    sp.attrs["fired"] = ",".join(opt.fired)
                    sp.attrs["stages"] = 1
            src._force()
            # a reduce binding may name a source column the maps never
            # touch — ragged sources can't feed a block program, and
            # that is exactly what the legacy path would reject too
            # (column_block raises inside _lower_hoisted_reduce)
            out = _lower_hoisted_reduce(
                src, stage, gr, r_binding, ledger=ledger
            )
            return True, out, src.num_rows
    if ledger is not None:
        # journaled reduce over an unhoistable chain: let the caller's
        # legacy path force the frame and journal per partition
        return False, None, None
    # no hoist: materialize a demand-pruned view (fusion/pruning still
    # apply) and run the ordinary eager reduce over it
    view = pruned_view(dframe, demand)
    return True, ops_mod._reduce_blocks_impl(fetches, view, None), view.num_rows


# ---------------------------------------------------------------------------
# journal integration: one fused plan = one canonical job
# ---------------------------------------------------------------------------


def lower_for_job(frame: TensorFrame):
    """Lower a planned lazy frame into ``(op, fetches, data, constants,
    post)`` for the durable-job layer: ``op``/``fetches``/``data``/
    ``constants`` feed the ordinary journaled engine path (one composite
    graph = one canonical manifest fingerprint, deterministic across
    processes), and ``post(frame)`` applies any trailing ``select``/
    ``filter_rows`` nodes to the assembled result (skipped, with a
    warning, on quarantine-shortened partials whose row positions no
    longer line up). Raises ``ValueError`` when ``frame`` is not a
    pending planned pipeline."""
    node = _planned(frame)
    if node is None:
        raise ValueError(
            "run_job('pipeline', ...) needs a pending lazy planned frame "
            "(a chain of map/select/filter ops that has not been forced); "
            "got a concrete or non-planned frame"
        )
    src, ops = _chain(node)
    map_ops = [o for o in ops if o.kind in _MAP_KINDS]
    if not map_ops:
        raise ValueError(
            "a pipeline job needs at least one map op in the chain"
        )
    # post-ops may only TRAIL the maps: the journaled unit is the fused
    # map program, and select/filter replay deterministically on top
    seen_post = False
    for o in ops:
        if o.kind in _MAP_KINDS:
            if seen_post:
                raise ValueError(
                    "pipeline jobs support select/filter only AFTER the "
                    "map chain (a mid-chain projection changes the "
                    "journaled program; force the frame instead)"
                )
        else:
            seen_post = True
    demand = {c.name for c in node.result_info}
    cfg = _cfg()
    opt = _optimize(src, map_ops, _demand_above_posts(ops, demand), cfg)
    if len(opt.stages) != 1:
        raise ValueError(
            "pipeline jobs need the map chain to lower to one fused "
            "program (enable Config.plan_fuse_maps)"
        )
    _record_metrics(opt)
    stage = opt.stages[0]
    if stage.kind == "map":
        op = stage.ops[0]
        fetches: Any = op.graph
        kind = op.kind
        consts = op.constants
    else:
        composite, const_feed = _composite_for(stage, src.schema)
        fetches = composite
        kind = stage.group_kind
        consts = const_feed or None
    post_ops = [o for o in ops if o.kind not in _MAP_KINDS]
    leaf = node

    n_rows_full = src.num_rows

    def post(result: Optional[TensorFrame]) -> Optional[TensorFrame]:
        if result is None:
            return None
        cur = result
        if cur.num_rows != n_rows_full:
            # quarantined blocks dropped rows from the partial result,
            # so a recorded filter mask (and row-aligned conform) no
            # longer lines up with the surviving rows — applying it
            # would silently select the WRONG rows. Surface the partial
            # result untouched; the quarantine records say what's
            # missing, and a resume_job(retry_quarantined=True) after a
            # fix yields the full, post-processed pipeline.
            if post_ops:
                logger.warning(
                    "pipeline job: %d trailing select/filter node(s) "
                    "NOT applied to a quarantine-shortened partial "
                    "result (%d of %d rows survive); re-run after "
                    "clearing the quarantine for the full pipeline",
                    len(post_ops), cur.num_rows, n_rows_full,
                )
            return cur
        for o in post_ops:
            if o.kind == "select":
                cur = cur.select(*o.select_cols)
            else:
                cur = cur.filter_rows(o.filter_mask)
        if leaf.kind in _MAP_KINDS:
            cur = _conform(cur, leaf.result_info)
        return cur

    return kind, fetches, src, consts, post


def _demand_above_posts(ops: List[PlanOp], demand: Set[str]) -> Set[str]:
    """Walk trailing select/filter nodes to translate leaf demand into
    demand at the top post-op boundary (select renames)."""
    needed = set(demand)
    for o in reversed(ops):
        if o.kind == "select":
            needed = {s for s, d in o.select_cols if d in needed}
        elif o.kind == "filter_rows":
            continue
        else:
            break
    return needed


# ---------------------------------------------------------------------------
# explain
# ---------------------------------------------------------------------------


def explain_plan(dframe: TensorFrame) -> Optional[str]:
    """Render the logical plan of a pending planned frame: the recorded
    nodes, which rewrite passes would fire, the pruned columns, and the
    fused program count. Returns None for non-planned frames (the
    caller falls back to schema-only output). Pure — no execution, no
    metrics."""
    node = _planned(dframe)
    if node is None:
        return None
    src, ops = _chain(node)
    cfg = _cfg()
    demand = {c.name for c in node.result_info}
    lines = ["== Logical plan =="]
    srepr = (
        f"source: {len(src.schema.names)} column(s) "
        f"{src.schema.names}"
        + (" [lazy]" if src.is_lazy else f" rows={src._num_rows}")
    )
    lines.append(srepr)
    for i, op in enumerate(ops, 1):
        if op.kind in _MAP_KINDS:
            binds = ", ".join(
                f"{ph}<-{col}" for ph, col in sorted(op.binding.items())
            )
            extra = " const" if op.constants else ""
            lines.append(
                f" {i}. {op.kind} fetches={sorted(op.fetch_names)} "
                f"binds[{binds}]{extra}"
            )
        elif op.kind == "select":
            proj = ", ".join(
                s if s == d else f"{s} as {d}" for s, d in op.select_cols
            )
            lines.append(f" {i}. select [{proj}]")
        else:
            n_keep = int(np.count_nonzero(op.filter_mask))
            lines.append(
                f" {i}. filter_rows [{n_keep}/{len(op.filter_mask)} rows]"
            )
    if not cfg.plan_lazy_ops:
        lines.append("== Optimized ==")
        lines.append(" (plan layer disabled: Config.plan_lazy_ops=False;")
        lines.append("  ops execute one at a time)")
        return "\n".join(lines)
    opt = _optimize(src, ops, demand, cfg)
    lines.append("== Optimized ==")
    lines.append(
        " passes fired: " + (", ".join(opt.fired) if opt.fired else "none")
    )
    if opt.dropped_ops:
        lines.append(
            f" pruned ops: {opt.dropped_ops} "
            f"(dead fetches: {opt.dead_fetches})"
        )
    if opt.pruned_source_cols:
        lines.append(
            f" pruned source columns (never uploaded): "
            f"{opt.pruned_source_cols}"
        )
    programs = 0
    for i, stage in enumerate(opt.stages, 1):
        if stage.kind == "fused":
            programs += 1
            lines.append(
                f" stage {i}: fused {stage.group_kind} "
                f"[{len(stage.ops)} ops -> 1 program] "
                f"fetches={list(stage.out_fetches)}"
            )
        elif stage.kind == "map":
            programs += 1
            op = stage.ops[0]
            lines.append(
                f" stage {i}: {op.kind} fetches={sorted(op.fetch_names)}"
            )
        elif stage.kind == "select":
            proj = ", ".join(
                s if s == d else f"{s} as {d}"
                for s, d in stage.ops[0].select_cols
            )
            lines.append(f" stage {i}: select [{proj}]")
        else:
            lines.append(f" stage {i}: filter_rows")
    lines.append(f" fused programs: {programs}")
    return "\n".join(lines)
