"""The local (single-host) execution engine: the nine-function public API.

Analog of the reference's ``DebugRowOps`` execution engine
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala:281-593``)
re-designed for XLA:

- where the reference opens a TF ``Session`` per Spark task and feeds NIO
  buffers through JNI (``performMap``, ``DebugRowOps.scala:766-803``), this
  engine jits the captured program once and executes it per partition block;
  XLA's jit cache plays the role of the broadcast graph + session pool;
- where the reference merges reduce partials two rows at a time *on the
  driver* through a local session (``reducePairBlock``,
  ``DebugRowOps.scala:741-750``), this engine folds partials on device with
  one fixed ``[2, ...]``-shaped merge program (and, distributed, replaces
  the fold with collectives — see ``tensorframes_tpu.parallel``);
- where the reference's ``TensorFlowUDAF`` buffers rows per group and
  compacts through TF when full (``DebugRowOps.scala:601-695``), ``aggregate``
  computes per-row partials with ``vmap`` and combines them with a single
  *segmented associative scan* on device — one XLA program for any number of
  groups, instead of a JVM shuffle.

Semantics parity: lazy maps / eager reduces (``Operations.scala:20-135``),
fetches name the new columns, collisions error, no implicit casting, reduce
naming conventions ``x_input`` / ``x_1``+``x_2``, trim maps may change the
row count (``TrimmingOperationsSuite.scala:25-39``).
"""

from __future__ import annotations

import inspect
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..capture import CapturedGraph, Node, TensorSpec, build_graph
from ..capture import dsl as _dsl
from ..frame import GroupedFrame, TensorFrame
from ..frame import transfer as _transfer
from ..frame.table import _build_column, _ColumnData
from ..obs import span as _span
from ..obs import programs as _programs
from ..obs.metrics import counter as _counter
from ..schema import ColumnInfo, FrameInfo, Shape, Unknown
from ..utils import ensure_x64, get_logger
from ..utils.failures import record_oom_split
from .validation import (
    InputNotFoundError,
    InvalidDimensionError,
    check_output_collisions,
    resolve_column,
    validate_map_inputs,
    validate_reduce_block_graph,
    validate_reduce_row_graph,
)

__all__ = [
    "map_blocks",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "aggregate",
    "analyze",
    "print_schema",
    "explain",
    "block",
    "row",
]

logger = get_logger("engine")

# re-export the auto-placeholder helpers at the API level (reference
# ``core.py:397-450``)
block = _dsl.block
row = _dsl.row

#: per-callable CapturedGraph memo (see _graph_from_callable)
_callable_graphs: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
#: (code, spec) signatures already captured once — used to warn (once per
#: signature) on recompile churn from lambdas recreated per call
_seen_callable_codes: set = set()
_warned_callable_codes: set = set()

# -- engine telemetry (tensorframes_tpu.obs; docs/observability.md) ---------
_m_graph_hits = _counter(
    "engine.graph_memo_hits_total",
    "Callable-frontend captures resolved from the per-function memo",
)
_m_graph_misses = _counter(
    "engine.graph_memo_misses_total",
    "Callable-frontend captures that traced a fresh CapturedGraph",
)
_m_recapture = _counter(
    "engine.callable_recapture_total",
    "Re-captures of identical code under a new function identity "
    "(recompile churn: a lambda recreated per call)",
)
_m_jit_builds = _counter(
    "engine.jit_cache_builds_total",
    "jax.jit wrappers built for a CapturedGraph (first use)",
)
_m_jit_reuse = _counter(
    "engine.jit_cache_reuse_total",
    "Engine calls that reused a CapturedGraph's existing jit wrapper",
)
_m_rows = _counter(
    "engine.rows_processed_total",
    "Input rows processed, by op",
    labels=("op",),
)
_m_blocks = _counter(
    "engine.blocks_processed_total",
    "Device dispatches (partition blocks / row chunks), by op",
    labels=("op",),
)
# pre-bound series for the dispatch loops (label resolution paid once)
_m_blocks_map_blocks = _m_blocks.bind(op="map_blocks")
_m_blocks_map_rows = _m_blocks.bind(op="map_rows")
_m_rows_map_blocks = _m_rows.bind(op="map_blocks")
_m_rows_map_rows = _m_rows.bind(op="map_rows")


# ---------------------------------------------------------------------------
# graph normalization: Node(s) | CapturedGraph | plain callable
# ---------------------------------------------------------------------------


def _as_graph(
    fetches,
    df: TensorFrame,
    *,
    cell_inputs: bool,
    feed_dict: Optional[Dict[str, str]] = None,
    constants: Optional[Dict[str, Any]] = None,
    schema: Optional[FrameInfo] = None,
) -> CapturedGraph:
    """Accept the three frontend forms and return a CapturedGraph.

    ``cell_inputs=False``: placeholders for a plain callable get *block*
    shapes (lead Unknown); ``True``: cell shapes (map_rows / reduce_rows).
    ``constants``: placeholder name -> host array fed per call instead of a
    column — unlike DSL constants (baked into the program, forcing a
    recompile when the value changes) these are ordinary traced arguments,
    so iterative algorithms reuse one compiled program (e.g. k-means
    centroids each Lloyd step)."""
    if isinstance(fetches, CapturedGraph):
        g = fetches
    elif isinstance(fetches, Node):
        g = build_graph([fetches])
    elif isinstance(fetches, (list, tuple)) and fetches and all(
        isinstance(f, Node) for f in fetches
    ):
        g = build_graph(list(fetches))
    elif callable(fetches):
        g = _graph_from_callable(
            fetches, df, cell_inputs, feed_dict, constants, schema=schema
        )
    else:
        raise TypeError(
            f"fetches must be Node(s), a CapturedGraph, or a callable; got "
            f"{type(fetches).__name__}"
        )
    if feed_dict:
        # memoize the renamed wrapper on the underlying graph: a fresh
        # CapturedGraph per call would drop every jitted-program cache
        # attached to it and recompile on each invocation
        fd_key = tuple(sorted(feed_dict.items()))
        cache = getattr(g, "_with_inputs_cache", None)
        if cache is None:
            cache = g._with_inputs_cache = {}
        if fd_key not in cache:
            cache[fd_key] = g.with_inputs(feed_dict)
        g = cache[fd_key]
    return g


#: fn -> bindable parameter names. inspect.signature costs ~70us per call
#: — measurable against a ~3ms scoring pass — and a function's signature
#: cannot change, so it is resolved once per function object.
_fn_params_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _fn_params(fn: Callable) -> List[str]:
    try:
        cached = _fn_params_cache.get(fn)
    except TypeError:  # unhashable/unweakrefable callable: resolve inline
        cached = None
    if cached is None:
        cached = [
            p.name
            for p in inspect.signature(fn).parameters.values()
            if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
        ]
        try:
            _fn_params_cache[fn] = cached
        except TypeError:
            pass
    return cached


def _graph_from_callable(
    fn: Callable,
    df: TensorFrame,
    cell_inputs: bool,
    feed_dict: Optional[Dict[str, str]],
    constants: Optional[Dict[str, Any]] = None,
    schema: Optional[FrameInfo] = None,
) -> CapturedGraph:
    """Plain-function frontend: parameter names are placeholder names, bound
    to columns directly or via feed_dict / reduce suffixes, or to per-call
    ``constants`` arrays."""
    from ..schema import for_numpy_dtype

    schema = schema if schema is not None else df.schema
    params = _fn_params(fn)
    specs: Dict[str, Tuple] = {}
    bound: Dict[str, str] = {}
    missing = []
    for p in params:
        if constants and p in constants:
            arr = np.asarray(constants[p])
            specs[p] = (for_numpy_dtype(arr.dtype), Shape(arr.shape))
            continue
        col = resolve_column(p, feed_dict or {}, schema.names)
        if col is None:
            missing.append(p)
            continue
        bound[p] = col
        info = schema[col]
        if cell_inputs:
            shape = info.cell_shape
        elif p.endswith("_input"):
            # block-reduce convention: one dim higher than the cell
            shape = info.cell_shape.prepend(Unknown)
        else:
            shape = info.block_shape.with_lead(Unknown)
        specs[p] = (info.scalar_type, shape)
    if missing:
        raise InputNotFoundError(missing, schema.names)
    # memoize per function object + spec signature: a fn defined once and
    # passed to an op repeatedly (e.g. inside an iterative algorithm) keeps
    # one CapturedGraph and therefore one compiled program
    cache_key = (
        cell_inputs,
        tuple(sorted((k, st.name, s.dims) for k, (st, s) in specs.items())),
        tuple(sorted((feed_dict or {}).items())),
    )
    try:
        per_fn = _callable_graphs.setdefault(fn, {})
    except TypeError:  # unhashable/unweakrefable callables skip the cache
        per_fn = {}
    if cache_key in per_fn:
        _m_graph_hits.inc()
        return per_fn[cache_key]
    _m_graph_misses.inc()
    # capture is memoized by FUNCTION IDENTITY; a lambda recreated inside a
    # loop has the same code but a new identity every pass, silently
    # recompiling its programs. Detect the churn and tell the user once.
    # Bound methods, closures, and default-args carriers legitimately share
    # code across distinct functions, so only bare code-only functions warn.
    code = getattr(fn, "__code__", None)
    if (
        code is not None
        and getattr(fn, "__closure__", True) is None
        and getattr(fn, "__defaults__", True) is None
        and not hasattr(fn, "__self__")
    ):
        code_key = (code, cache_key)
        if code_key in _seen_callable_codes:
            # the log line fires once per signature; the counter counts
            # EVERY recapture, so churn magnitude stays measurable after
            # the warning has been emitted
            _m_recapture.inc()
            if code_key not in _warned_callable_codes:
                _warned_callable_codes.add(code_key)
                logger.warning(
                    "capturing %s again for identical code — it is a new "
                    "function object each call, so compiled programs are "
                    "not reused; define the function once and pass the "
                    "same object to avoid recompilation",
                    getattr(fn, "__qualname__", fn),
                )
        elif len(_seen_callable_codes) < 4096:  # bounded diagnostic state
            _seen_callable_codes.add(code_key)
    probe_feed = None
    if any(st.name == "binary" for st, _ in specs.values()):
        # binary programs cannot be abstract-traced; discover outputs by
        # running on the first row's real cells (host path)
        if df.num_rows == 0:
            raise ValueError("cannot capture a binary-input program on an empty frame")
        probe_feed = {p: df.column_data(c).cell(0) for p, c in bound.items()}
    g = CapturedGraph.from_callable(fn, specs, probe_feed=probe_feed)
    per_fn[cache_key] = g
    return g


#: monotonically increasing program sequence — the cost-registry key
#: component that keeps two graphs with identical labels distinct
#: (id() can be recycled after GC; this cannot). Lock-guarded: two
#: threads forcing ops concurrently must not mint one seq for two
#: graphs and merge their cost records.
_prog_seq = 0
_prog_seq_lock = threading.Lock()


def _program_key(g: CapturedGraph, variant: str) -> Tuple[str, str]:
    """(key, name) for a graph's compiled program in the cost registry
    (``obs/programs.py``). Fused plan composites carry a ``plan_label``
    set by ``engine/plan.py``; plain graphs are named by their
    fetches."""
    global _prog_seq
    with _prog_seq_lock:
        seq = getattr(g, "_prog_seq", None)
        if seq is None:
            _prog_seq += 1
            seq = g._prog_seq = _prog_seq
    label = getattr(g, "plan_label", None)
    if not label:
        fetches = ",".join(list(getattr(g, "fetch_names", ())) or ["anon"])
        label = f"engine:{fetches}"
    if variant:
        label = f"{label}:{variant}"
    return f"g{seq}:{label}", label


def _jitted(g: CapturedGraph):
    j = getattr(g, "_jit_cache", None)
    if j is None:
        import jax

        key, name = _program_key(g, "")
        j = _programs.instrument(
            jax.jit(g.fn), key=key, name=name, kind="engine.block",
        )
        g._jit_cache = j
        _m_jit_builds.inc()
    else:
        _m_jit_reuse.inc()
    return j


def _jitted_vmap(g: CapturedGraph):
    j = getattr(g, "_jit_vmap_cache", None)
    if j is None:
        import jax

        key, name = _program_key(g, "vmap")
        j = _programs.instrument(
            jax.jit(jax.vmap(g.fn)), key=key, name=name, kind="engine.row",
        )
        g._jit_vmap_cache = j
        _m_jit_builds.inc()
    else:
        _m_jit_reuse.inc()
    return j


def _feeder_streams_host(cd) -> bool:
    """Whether :func:`_block_feeder` would stream HOST slices for this
    dense column (over the device-cache budget) — checkable WITHOUT
    building the feeder, because building one for an in-budget column
    starts its chunked device upload as a side effect."""
    from ..frame.table import _is_device_array
    from ..utils import get_config

    dense = cd.dense
    return (
        not _is_device_array(dense)
        and dense.nbytes > get_config().device_cache_bytes
    )


def _block_feeder(cd):
    """Per-partition feed source for a dense column, plus whether it streams.

    Returns ``(feed_fn, streams_host)``: a chunked-upload stream slicer
    when the column fits the device-cache budget (the first blocks
    compute while later transfer chunks are still in the air; once every
    chunk has landed the memoized assembled column feeds exactly like
    the old whole-``device_put`` copy), else host slices streamed one
    block at a time so HBM stays bounded by a single block.
    Device-resident columns (results of a previous op) feed directly — no
    transfer, no budget check. NOTE: building the stream slicer STARTS
    the column's upload — callers that may still bail out of their pass
    must run every bail-out check first (``_feeder_streams_host`` covers
    the budget check side-effect-free)."""
    from ..frame.table import _is_device_array

    def _slicer(arr):
        # a [0:n] slice of a device array is an eager on-device copy — for
        # a single-partition frame that would double the pass's HBM
        # traffic, so the full range returns the array itself
        n = arr.shape[0]
        return lambda lo, hi: arr if lo == 0 and hi == n else arr[lo:hi]

    dense = cd.dense
    if _is_device_array(dense):
        return _slicer(dense), False
    if not _feeder_streams_host(cd):
        return cd.device_stream().slice, False
    return (lambda lo, hi: dense[lo:hi]), True


def _ensure_precision(g: CapturedGraph, schema: FrameInfo) -> None:
    if any(p.scalar_type.is_64bit for p in g.placeholders.values()) or any(
        c.scalar_type.is_64bit for c in schema
    ):
        ensure_x64()


def _fetch_column_info(name: str, spec: TensorSpec, block_output: bool) -> ColumnInfo:
    """Result-column schema for a fetch (reference embeds the output shape in
    the new column's metadata, ``DebugRowOps.scala:349-360``)."""
    if block_output:
        shape = spec.shape
        nesting = max(spec.shape.num_dims - 1, 0)
    else:
        shape = spec.shape.prepend(Unknown)
        nesting = spec.shape.num_dims
    return ColumnInfo(
        name, spec.scalar_type, analyzed_shape=shape, nesting=nesting
    )


def _empty_output(spec: TensorSpec, block_output: bool) -> np.ndarray:
    cell = spec.shape.tail() if block_output else spec.shape
    dims = tuple(0 if d == Unknown else d for d in cell.dims)
    return np.zeros((0,) + dims, dtype=spec.scalar_type.np_dtype)


# ---------------------------------------------------------------------------
# map_blocks
# ---------------------------------------------------------------------------


def _resolve_decoder_cols(
    decoders: Dict[str, Callable],
    feed_dict: Optional[Dict[str, str]],
    schema_names: Sequence[str],
) -> Dict[str, Callable]:
    """Decoder keys are column names, or placeholder names routed through
    ``feed_dict`` (explicit feed_dict routing wins: a placeholder may
    collide with an unrelated column name). Returns column -> codec."""
    out: Dict[str, Callable] = {}
    for key, fn in decoders.items():
        if feed_dict and key in feed_dict:
            col = feed_dict[key]
        elif key in schema_names:
            col = key
        else:
            raise InputNotFoundError([key], schema_names)
        out[col] = fn
    return out


#: partitions of decoded blocks kept in flight ahead of the device: decode
#: of partition p+1..p+N proceeds on the host pool while the chip runs p
_DECODE_PREFETCH = 4

#: host-streamed (over-budget) columns keep this many partition uploads
#: in flight ahead of the device: block i+1 crosses the link while the
#: chip runs block i (the streaming-ingest overlap). ONE ahead — these
#: blocks belong to a column that exceeded the device-cache budget, so
#: the streaming contract of ~one resident block loosens to exactly two
#: (current + next), the minimum that buys any overlap at all
_UPLOAD_PREFETCH = 1


def map_blocks(
    fetches,
    dframe: TensorFrame,
    trim: bool = False,
    feed_dict: Optional[Dict[str, str]] = None,
    constants: Optional[Dict[str, Any]] = None,
    decoders: Optional[Dict[str, Callable]] = None,
    _ledger=None,
    _plan: bool = True,
) -> TensorFrame:
    """Transform the frame block by block; fetches become new columns
    (``trim=False``) or the entire output (``trim=True``, row count may
    change). Lazy, like the reference (``core.py:266-309``).

    Each partition block is one XLA program execution; XLA's jit cache keys
    on the block shape, so frames with equal-sized partitions compile once.
    ``constants`` feed placeholders with per-call host arrays (same shape ->
    no recompile), for iterative algorithms like k-means centroids.

    ``decoders`` maps a binary column (or its placeholder) to a host codec
    ``bytes -> array``; that column then feeds the program as decoded
    numeric blocks, with decode running on a thread pool several
    partitions AHEAD of the device — host codec work overlaps chip compute
    instead of serializing before it (the reference gets this overlap from
    Spark's partition iterator feeding the TF session,
    ``DebugRowOps.scala:766-803``; here it is explicit double-buffering).
    The decoded shape/dtype is probed from row 0; all rows must decode to
    that shape (varying shapes: use ``map_rows``, which shape-buckets).
    The result frame carries the ORIGINAL (undecoded) columns — decoded
    blocks are transient feed buffers, never a materialized column.

    ``_ledger`` (private) is the durable-job hook: ``engine/jobs.py``
    threads a :class:`~tensorframes_tpu.engine.jobs.BlockLedger` through
    the partition loop so completed partitions restore from / spool to a
    journal and poisoned partitions quarantine instead of killing the
    job (docs/fault_tolerance.md).
    """
    decode_fns: Dict[str, Callable] = {}
    probe_cells: Dict[str, np.ndarray] = {}
    schema = dframe.schema
    if decoders:
        from ..frame.table import _as_cell
        from ..schema import for_numpy_dtype

        decode_fns = _resolve_decoder_cols(
            decoders, feed_dict, schema.names
        )
        if dframe.num_rows == 0:
            raise ValueError(
                "map_blocks(decoders=...) on an empty frame (no row to "
                "probe the decoded schema from)"
            )
        infos = []
        for ci in schema:
            if ci.name in decode_fns:
                probe = _as_cell(
                    decode_fns[ci.name](
                        dframe.column_data(ci.name).cell(0)
                    )
                )
                if isinstance(probe, bytes):
                    raise TypeError(
                        f"decoder for column {ci.name!r} produced bytes; "
                        f"block programs need numeric cells"
                    )
                probe_cells[ci.name] = probe
                infos.append(
                    ColumnInfo(
                        ci.name,
                        for_numpy_dtype(probe.dtype),
                        analyzed_shape=Shape(
                            [Unknown] + list(probe.shape)
                        ),
                        nesting=probe.ndim,
                    )
                )
            else:
                infos.append(ci)
        schema = FrameInfo(infos)
    g = _as_graph(
        fetches, dframe, cell_inputs=False, feed_dict=feed_dict,
        constants=constants, schema=schema,
    )
    # the validate/analyze/result-schema prologue depends only on
    # (graph, schema, trim, constant names) — memoize it on the graph so
    # chained passes over the same frame (the pipeline steady state) pay
    # a dict lookup, not a re-derivation. Keys hold the schema objects
    # themselves, so an id() collision after GC cannot alias. Decoder
    # passes rebuild their probe schema per call and naturally miss.
    plan_key = (id(schema), id(dframe.schema), trim,
                tuple(sorted(constants or ())))
    plan_cache = getattr(g, "_map_plan_cache", None)
    if plan_cache is None:
        from collections import OrderedDict

        plan_cache = g._map_plan_cache = OrderedDict()
    hit = plan_cache.get(plan_key)
    if hit is not None and hit[0] is schema and hit[1] is dframe.schema:
        plan_cache.move_to_end(plan_key)  # LRU, like _generate_cache
        _, _, binding, out_specs, fetch_names, result_info = hit
    else:
        binding = validate_map_inputs(
            g, schema, block=True, constants=set(constants or ())
        )
        # ragged/binary columns are rejected when blocks are materialized
        # in the thunk (column_block raises), keeping construction
        # metadata-only/lazy
        _ensure_precision(g, schema)
        input_shapes = {
            ph: schema[col].block_shape.with_lead(Unknown)
            for ph, col in binding.items()
        }
        out_specs = g.analyze(input_shapes)
        for name, spec in out_specs.items():
            if spec.shape.num_dims == 0:
                raise InvalidDimensionError(
                    f"map_blocks output {name!r} is a scalar; map outputs "
                    f"must keep the leading row dimension (use "
                    f"reduce_blocks to reduce a frame to one row)"
                )
        if not trim:
            check_output_collisions(out_specs, dframe.schema)

        fetch_names = sorted(out_specs)  # outputs sorted by name (ref)
        fetch_infos = [
            _fetch_column_info(n, out_specs[n], block_output=True)
            for n in fetch_names
        ]
        if trim:
            result_info = FrameInfo(fetch_infos)
        else:
            result_info = FrameInfo(fetch_infos + list(dframe.schema))
        # decoder passes rebuild their probe schema per call, so their
        # entries could never hit — don't insert them
        if not decode_fns:
            while len(plan_cache) >= 64:  # bound; evict oldest
                plan_cache.popitem(last=False)
            plan_cache[plan_key] = (
                schema, dframe.schema, binding, out_specs, fetch_names,
                result_info,
            )

    jit_fn = _jitted(g)
    parent = dframe

    const_feed = {
        ph: np.asarray(v) for ph, v in (constants or {}).items()
    }

    def _run() -> TensorFrame:
        from ..utils import get_config

        pieces: Dict[str, List] = {n: [] for n in fetch_names}
        part_sizes: List[int] = []
        # decoded columns feed through a PREFETCHING codec: partition p's
        # block is decoded on the pool while the chip still runs earlier
        # partitions, and decode for p+1..p+N is submitted the moment p's
        # block is consumed
        decode_pool = None
        decode_futs: Dict[Tuple[str, int], Any] = {}
        bounds = list(parent.partition_bounds())
        part_of = {tuple(b): i for i, b in enumerate(bounds)}

        def _submit_decode(col: str, p: int) -> None:
            if (col, p) in decode_futs or p >= len(bounds):
                return
            lo, hi = bounds[p]
            fn = decode_fns[col]
            cd = parent.column_data(col)
            pc = probe_cells[col]

            def job(lo=lo, hi=hi, fn=fn, cd=cd, pc=pc):
                if hi == lo:
                    return np.empty((0,) + pc.shape, dtype=pc.dtype)
                cells = []
                for i in range(lo, hi):
                    if i == 0:
                        # row 0 was decoded by the schema probe; reuse it
                        # (a stateful or expensive codec must not run
                        # twice per row)
                        cells.append(np.asarray(pc))
                        continue
                    c = np.asarray(fn(cd.cell(i)))
                    if c.shape != pc.shape:
                        raise ValueError(
                            f"decoder for column {col!r} produced shape "
                            f"{c.shape} at row {i}, but row 0 probed "
                            f"{pc.shape}; block programs need uniform "
                            f"decoded shapes (use map_rows for varying "
                            f"ones)"
                        )
                    cells.append(c)
                return np.stack(cells).astype(pc.dtype, copy=False)

            decode_futs[(col, p)] = decode_pool.submit(job)

        def _make_decode_feeder(col: str):
            def feeder(lo: int, hi: int) -> np.ndarray:
                p = part_of[(lo, hi)]
                _submit_decode(col, p)
                for q in range(p + 1, p + 1 + _DECODE_PREFETCH):
                    _submit_decode(col, q)
                return decode_futs.pop((col, p)).result()

            return feeder

        # host-streamed (over-budget) columns upload through a PREFETCHING
        # pipeline: partition p+1's block crosses the link while the chip
        # runs p, each block retried per chunk by the transfer layer —
        # the same submit/pop state machine as the decode prefetch above
        # (a recovery re-run of a consumed partition simply resubmits)
        upload_pool = None
        upload_futs: Dict[Tuple[str, int], Any] = {}

        def _submit_upload(
            ph: str, p: int, host_feed, prefetch: bool = False
        ) -> None:
            if (ph, p) in upload_futs or p >= len(bounds):
                return
            if prefetch and _ledger is not None and _ledger.peek(p) != "todo":
                # journaled pass: restored/quarantined blocks never
                # recompute, so their bytes must never cross the link
                # (the demanded block itself is always todo — only the
                # speculative window consults the ledger)
                return
            lo, hi = bounds[p]
            if hi == lo:
                return
            upload_futs[(ph, p)] = upload_pool.submit(
                _transfer.h2d, host_feed(lo, hi), f"map_blocks block {p}"
            )

        def _make_upload_feeder(ph: str, host_feed):
            def feeder(lo: int, hi: int):
                p = part_of[(lo, hi)]
                _submit_upload(ph, p, host_feed)
                for q in range(p + 1, p + 1 + _UPLOAD_PREFETCH):
                    _submit_upload(ph, q, host_feed, prefetch=True)
                return upload_futs.pop((ph, p)).result()

            return feeder

        # device-resident columns when they fit; streamed blocks otherwise
        feeders = {}
        streaming = False
        streamed_phs: List[str] = []
        for ph, col in binding.items():
            if col in decode_fns:
                if decode_pool is None:
                    import os
                    from concurrent.futures import ThreadPoolExecutor

                    decode_pool = ThreadPoolExecutor(
                        min(32, os.cpu_count() or 1)
                    )
                feeders[ph] = _make_decode_feeder(col)
                continue
            parent.column_block(col, None)  # rejects ragged/binary
            feeders[ph], streams = _block_feeder(parent.column_data(col))
            if streams:
                streamed_phs.append(ph)
            streaming = streaming or streams
        if streamed_phs:
            from concurrent.futures import ThreadPoolExecutor

            # one waited block + the prefetch window PER streamed column:
            # a shared too-small pool would queue column B's current
            # block behind column A's prefetch and serialize the pass
            upload_pool = ThreadPoolExecutor(
                min(16, (1 + _UPLOAD_PREFETCH) * len(streamed_phs)),
                thread_name_prefix="tft-upload-prefetch",
            )
            for ph in streamed_phs:
                feeders[ph] = _make_upload_feeder(ph, feeders[ph])
        # Outputs stay device-resident only when HBM stays bounded: if any
        # input streams from the host (over-budget column), or the full
        # output itself would blow the device-cache budget, pull each
        # partition's result to host as it lands (the pre-device-residency
        # behavior), keeping peak HBM at ~one block.
        budget = get_config().device_cache_bytes
        if not streaming and not trim:
            est = 0
            for spec in out_specs.values():
                cell = spec.shape.tail()
                if all(d != Unknown for d in cell.dims):
                    est += (
                        int(np.prod(cell.dims)) if cell.dims else 1
                    ) * spec.scalar_type.np_dtype.itemsize * parent.num_rows
            streaming = est > budget
        if _ledger is not None:
            # journaled jobs: a deterministic per-partition block plan,
            # and host materialization per block (results spool to the
            # journal, so device residency buys nothing here)
            _ledger.ensure_plan(
                [{"rows": hi - lo, "lo": lo, "hi": hi} for lo, hi in bounds],
                graph=g, schema=schema, rows=parent.num_rows,
                extra={"trim": trim},
            )
            streaming = True
        # trim maps and Unknown-dim fetches have no static size estimate:
        # track actual accumulated bytes and demote to host streaming the
        # moment the budget is crossed mid-run
        acc_bytes = 0
        # streaming materialization is WINDOWED (double-buffered): pulling a
        # partition's output to host blocks the host until that transfer
        # lands, so materializing the append immediately would serialize
        # transfer against the next partition's dispatch. Keeping a couple
        # of partitions in flight lets the device run ahead while earlier
        # outputs stream down; peak HBM stays at ~window+1 blocks, which is
        # the streaming mode's contract.
        from collections import deque

        STREAM_WINDOW = 2
        pending: "deque[int]" = deque()
        #: pieces index -> partition index, so a failure surfacing at
        #: materialization can be traced back and re-run selectively
        piece_part: List[int] = []

        def compute_partition(p: int):
            """Dispatch one partition's program (feed assembly included) —
            called by the main loop AND by materialization-time recovery,
            so a lost async result re-runs only its own partition."""
            lo, hi = bounds[p]
            n = hi - lo
            _m_blocks_map_blocks.inc()
            from ..utils import is_oom, run_with_retries
            from ..utils.chaos import site as _chaos_site

            def dispatch():
                _chaos_site("engine.dispatch")
                out = jit_fn(feed)
                if _ledger is not None:
                    # journaled blocks materialize right after anyway;
                    # syncing INSIDE the retry window gives transient
                    # async failures retry coverage (the map_rows rule)
                    import jax

                    out = jax.block_until_ready(out)
                return out

            try:
                # feed assembly sits INSIDE the OOM envelope: for
                # host-streamed columns it includes the block's device
                # upload (prefetched or synchronous), and an OOM there
                # deserves the same repartition hint as one in compute
                feed = {ph: feeders[ph](lo, hi) for ph in binding}
                feed.update(const_feed)
                return run_with_retries(
                    dispatch, what=f"map_blocks partition {p}"
                )
            except Exception as e:
                if is_oom(e):
                    from ..utils.failures import DeviceOOMError

                    raise DeviceOOMError(
                        f"map_blocks partition {p} ({n} rows) exhausted "
                        f"device memory; repartition the frame into smaller "
                        f"blocks (block programs see a whole partition, so "
                        f"the engine cannot split one for you)"
                    ) from e
                raise

        def drain_pending(to_size: int) -> None:
            while len(pending) > to_size:
                idx = pending.popleft()
                try:
                    for nm in fetch_names:
                        pieces[nm][idx] = np.asarray(pieces[nm][idx])
                except Exception:
                    _recover_piece(idx)

        def _recover_piece(idx: int) -> None:
            """A transient failure during ASYNC execution surfaces when the
            partition's output is first touched; re-run just that
            partition (completed partitions are never recomputed) and
            materialize the replacement. Deterministic failures re-raise
            from the re-run itself."""
            p = piece_part[idx]
            logger.warning(
                "map_blocks partition %d result was lost to an async "
                "failure; re-running that partition only", p,
            )
            res = compute_partition(p)
            for nm in fetch_names:
                pieces[nm][idx] = np.asarray(res[nm])

        def _recover_lost_partitions() -> int:
            """Probe every partition's result; re-run the poisoned ones.
            Returns how many were recovered. EVERY fetch column is probed
            — an async failure can poison a single output buffer of a
            multi-output program, and probing only the first fetch would
            miss it (re-raising the original error instead of recovering)."""
            recovered = 0
            for idx in range(len(piece_part)):
                for nm in fetch_names:
                    probe = pieces[nm][idx]
                    try:
                        if hasattr(probe, "block_until_ready"):
                            probe.block_until_ready()
                        else:
                            np.asarray(probe)
                    except Exception:
                        _recover_piece(idx)  # re-runs ALL fetches for idx
                        recovered += 1
                        break
            return recovered

        try:
            for p in range(parent.num_partitions):
                lo, hi = bounds[p]
                n = hi - lo
                if n == 0:
                    part_sizes.append(0)
                    continue
                # NOTE: map_blocks keeps results device-resident so chained
                # passes pipeline without host syncs (the 20x headline win in
                # bench.py). Only errors raised at DISPATCH are retried here;
                # a failure during async execution surfaces later, at
                # materialization — where _recover_lost_partitions re-runs
                # just the partitions whose outputs were lost. map_rows
                # and the reduces, which materialize promptly, sync inside
                # their retry windows and get full coverage.
                if _ledger is not None:
                    st, res = _ledger.lookup(p)
                    if st == "quarantined":
                        part_sizes.append(0)
                        continue
                    if st == "todo":
                        res = _ledger.run_block(
                            p,
                            lambda p=p: {
                                nm: np.asarray(v)
                                for nm, v in compute_partition(p).items()
                                if nm in out_specs
                            },
                            rows=n,
                        )
                        if res is None:  # quarantined just now
                            part_sizes.append(0)
                            continue
                else:
                    res = compute_partition(p)
                # results stay device-resident: shape checks need no host sync,
                # and the host transfer happens only on host access (collect /
                # column host materialization) — chained ops feed from HBM
                out_n = None
                for name in fetch_names:
                    arr = res[name]
                    if not trim and arr.shape[0] != n:
                        raise ValueError(
                            f"map_blocks output {name!r} produced {arr.shape[0]} "
                            f"rows for a block of {n}; only trimmed maps may "
                            f"change the row count"
                        )
                    if trim and out_n is not None and arr.shape[0] != out_n:
                        raise ValueError(
                            f"map_blocks(trim=True) fetches disagree on the "
                            f"output row count in partition {p}: {name!r} "
                            f"produced {arr.shape[0]} rows, a previous fetch "
                            f"produced {out_n}"
                        )
                    out_n = arr.shape[0]
                    if not streaming:
                        acc_bytes += arr.nbytes
                        if acc_bytes > budget:
                            streaming = True
                            # demote what's accumulated — a lost async
                            # result can surface at these asarray calls
                            # too, so recover per piece like drain_pending
                            for idx in range(len(piece_part)):
                                try:
                                    for nm in fetch_names:
                                        pieces[nm][idx] = np.asarray(
                                            pieces[nm][idx]
                                        )
                                except Exception:
                                    _recover_piece(idx)
                    pieces[name].append(arr)
                piece_part.append(p)
                if streaming:
                    pending.append(len(pieces[fetch_names[0]]) - 1)
                    drain_pending(STREAM_WINDOW)
                part_sizes.append(out_n if trim else n)
            drain_pending(0)

            def build_cols() -> Dict[str, _ColumnData]:
                out: Dict[str, _ColumnData] = {}
                for name in fetch_names:
                    ps = pieces[name]
                    if not ps:
                        dense = _empty_output(
                            out_specs[name], block_output=True
                        )
                    elif len(ps) == 1:
                        dense = ps[0]
                    elif streaming:
                        dense = np.concatenate(ps, axis=0)
                    else:
                        import jax.numpy as jnp

                        dense = jnp.concatenate(ps, axis=0)  # on-device
                    out[name] = _ColumnData(dense=dense)
                return out

            try:
                cols = build_cols()
            except Exception:
                # an async-execution failure poisons its output buffers and
                # resurfaces here, at the concatenation that first touches
                # them: recover per partition and rebuild (decode feeders
                # are still alive — the pool shuts down in the finally)
                if _recover_lost_partitions() == 0:
                    raise  # not a lost-result failure; propagate as-is
                cols = build_cols()
        finally:
            if decode_pool is not None:
                decode_pool.shutdown(wait=False, cancel_futures=True)
            if upload_pool is not None:
                upload_pool.shutdown(wait=False, cancel_futures=True)
        offsets = np.concatenate([[0], np.cumsum(part_sizes)]).astype(np.int64)
        if trim:
            return TensorFrame(cols, result_info, offsets=offsets)
        dropped = (
            set(_ledger.quarantined_indices) if _ledger is not None else ()
        )
        if dropped:
            # quarantined partitions contribute no output rows, so the
            # carried-through parent columns must drop the same rows to
            # stay aligned (the partial-results contract)
            keep = np.concatenate(
                [
                    np.arange(lo, hi, dtype=np.int64)
                    for p, (lo, hi) in enumerate(bounds)
                    if p not in dropped
                ]
                or [np.empty(0, np.int64)]
            )
            for c in parent.schema:
                cols[c.name] = parent.column_data(c.name).take(keep)
        else:
            for c in parent.schema:
                cols[c.name] = parent.column_data(c.name)
        return TensorFrame(cols, result_info, offsets=offsets)

    def thunk() -> TensorFrame:
        with _span(
            "engine.map_blocks", partitions=parent.num_partitions, trim=trim
        ) as sp:
            out = _run()
            if sp is not None:
                sp.attrs["rows"] = parent.num_rows
        _m_rows_map_blocks.inc(parent.num_rows)
        return out

    if _plan and _ledger is None and not trim and not decode_fns:
        from . import plan as _plan_mod

        if _plan_mod.enabled():
            # record a logical-plan node: chained ops fuse/prune/hoist
            # at force time (docs/pipelines.md); trim maps and decoder
            # passes stay op-at-a-time (they change row counts / probe
            # host data) and act as chain boundaries
            return _plan_mod.make_lazy_map(
                "map_blocks", parent, g, binding, fetch_names,
                result_info, thunk, constants=constants,
            )
    return TensorFrame(
        {}, result_info, num_partitions=parent.num_partitions, _thunk=thunk
    )


def precompile(
    fetches,
    frame_or_schema,
    *,
    block_rows: Optional[Sequence[int]] = None,
    feed_dict: Optional[Dict[str, str]] = None,
    constants: Optional[Dict[str, Any]] = None,
) -> int:
    """Ahead-of-time compile the block programs a ``map_blocks`` call would
    dispatch, without moving any data.

    The reference never needed this — a TF 1.x session executes a GraphDef
    with zero compile cost (``TensorFlowOps.scala:76-95``) — but XLA
    compiles per (program, block shape), and on a fresh process that
    compile lands on the first data pass. With the persistent compilation
    cache (:func:`tensorframes_tpu.utils.enable_compilation_cache`, on by
    default) this both *warms* the on-disk cache and lets a serving
    process front-load all compilation before traffic:

    - pass a :class:`TensorFrame` and the partition block shapes are
      derived from it (one program per distinct partition size);
    - pass a :class:`FrameInfo` (e.g. for a graph loaded from an artifact
      via ``load_graph`` in a process that has no data yet) together with
      ``block_rows``, the partition sizes you will serve.

    Returns the number of distinct programs compiled. Compilation results
    land in XLA's in-process and persistent caches; the first real
    ``map_blocks`` pass then pays only executable-cache lookup.
    """
    import jax

    if isinstance(frame_or_schema, TensorFrame):
        df, schema = frame_or_schema, frame_or_schema.schema
        if block_rows is None:
            block_rows = [
                hi - lo for lo, hi in df.partition_bounds() if hi > lo
            ]
    elif isinstance(frame_or_schema, FrameInfo):
        df, schema = None, frame_or_schema
        if block_rows is None:
            raise ValueError(
                "precompile(schema) needs block_rows= (the partition sizes "
                "to compile for); pass a TensorFrame to derive them"
            )
    else:
        raise TypeError(
            f"frame_or_schema must be a TensorFrame or FrameInfo; got "
            f"{type(frame_or_schema).__name__}"
        )
    g = _as_graph(
        fetches, df, cell_inputs=False, feed_dict=feed_dict,
        constants=constants, schema=schema,
    )
    binding = validate_map_inputs(
        g, schema, block=True, constants=set(constants or ())
    )
    _ensure_precision(g, schema)
    for ph, col in binding.items():
        cell = schema[col].cell_shape
        if any(d == Unknown for d in cell.dims):
            raise ValueError(
                f"cannot precompile: column {col!r} has unknown cell "
                f"dims {cell}; analyze() the frame (or supply an analyzed "
                f"schema) first"
            )
    const_specs = {
        ph: jax.ShapeDtypeStruct(
            np.asarray(v).shape, np.asarray(v).dtype
        )
        for ph, v in (constants or {}).items()
    }
    jit_fn = _jitted(g)
    compiled = 0
    with _span("engine.precompile") as sp:
        for n in sorted(set(block_rows)):
            feed = {
                ph: jax.ShapeDtypeStruct(
                    (n, *schema[col].cell_shape.dims),
                    schema[col].scalar_type.np_dtype,
                )
                for ph, col in binding.items()
            }
            feed.update(const_specs)
            jit_fn.lower(feed).compile()
            compiled += 1
        if sp is not None:
            sp.attrs["programs"] = compiled
    return compiled


# ---------------------------------------------------------------------------
# map_rows
# ---------------------------------------------------------------------------


def _concat_dense(ps: List) -> Any:
    """Concatenate per-chunk result arrays into one dense column buffer:
    single piece passes through untouched (keeps device residency), any
    numpy piece forces a host concatenate, all-device pieces concatenate on
    device."""
    import jax.numpy as jnp

    if len(ps) == 1:
        return ps[0]
    if any(isinstance(p, np.ndarray) for p in ps):
        return np.ascontiguousarray(
            np.concatenate([np.asarray(p) for p in ps], axis=0)
        )
    return jnp.concatenate(ps, axis=0)


def _map_rows_thunk(
    parent: TensorFrame,
    binding: Dict[str, str],
    fetch_names: Sequence[str],
    out_specs: Dict[str, TensorSpec],
    result_info: FrameInfo,
    run_bucket: Callable[[Dict[str, np.ndarray], int], Dict[str, Any]],
    result_partitions: Optional[int] = None,
    device_resident: bool = True,
    ledger=None,
    graph=None,
    explicit_h2d: bool = False,
):
    """Shared row-map execution: bucket rows by input cell shape, assemble
    each bucket's batched feed (dense gather / ragged gather-pad / stack),
    run it through ``run_bucket(feed, m) -> {fetch: [m, ...] array}``, and
    scatter results back into row order. Used by both the local engine
    (vmap per bucket) and the distributed engine (shard_map-of-vmap with a
    main+tail split) so bucketing/ragged semantics cannot diverge.

    ``explicit_h2d`` (the local engine) moves each chunk's feed to device
    through the streaming transfer layer (``frame/transfer.py``) before
    dispatch: the upload is retried per transfer chunk, counted as link
    traffic, and chaos-injectable at ``frame.h2d`` — a transient tunnel
    error during ingest retries one chunk instead of killing the pass.
    The distributed engine keeps host feeds (its shard_map programs own
    their sharded placement).

    ``ledger`` (with ``graph`` for the manifest fingerprint) switches on
    durable-job execution (``engine/jobs.py``): the device-resident fast
    path is skipped in favor of a DETERMINISTIC block plan — fixed
    ``max_rows_per_device_call`` row slices, dense frames in row order,
    bucketed frames per bucket in first-appearance order — so a resumed
    job recomputes exactly the unfinished blocks and concatenates
    byte-identically to a clean run. Quarantined blocks drop their rows
    from the result (partial-results contract)."""

    def thunk() -> TensorFrame:
        from ..data import RaggedBuffer, gather_rows

        n = parent.num_rows
        if n == 0:
            if ledger is not None:
                ledger.ensure_plan(
                    [], graph=graph, schema=parent.schema, rows=0
                )
            cols = {
                name: _ColumnData(
                    dense=_empty_output(out_specs[name], block_output=False)
                )
                for name in fetch_names
            }
            for c in parent.schema:
                cols[c.name] = parent.column_data(c.name)
            return TensorFrame(cols, result_info)
        col_data = {ph: parent.column_data(col) for ph, col in binding.items()}
        # bucket rows by the tuple of input cell shapes (one compiled
        # program per bucket shape; the jit cache handles specialization).
        # Dense columns have ONE cell shape by construction, so their key
        # component is a constant — a frame of only dense columns is a
        # single bucket with no per-row work (and no host materialization
        # via cell()); only ragged columns' cells are visited.
        buckets: Dict[Tuple, List[int]] = {}
        dense_keys = {
            ph: cd.dense.shape[1:]
            for ph, cd in col_data.items()
            if cd.dense is not None
        }
        dense_fast = len(dense_keys) == len(col_data)
        if dense_fast:
            # the index list is only read by the fallback loop; build it
            # there (range(n) boxed as a 10M-int list is real memory)
            pass
        else:
            for i in range(n):
                key = tuple(
                    dense_keys[ph]
                    if ph in dense_keys
                    else col_data[ph].cells[i].shape
                    for ph in binding
                )
                buckets.setdefault(key, []).append(i)
        # ragged 1-D columns pack once into (flat, offsets) so bucket
        # stacking is a native gather instead of a Python stack loop
        ragged_bufs: Dict[str, RaggedBuffer] = {}
        for ph, cd in col_data.items():
            if cd.dense is None and cd.cells[0].ndim == 1:
                ragged_bufs[ph] = RaggedBuffer.from_cells(cd.cells)
        # dense_fast: chunks run in row order over the one bucket, so chunk
        # outputs concatenate straight into dense result columns — no
        # per-row scatter list, no _build_column re-stack of n cells
        dense_pieces: Dict[str, List[np.ndarray]] = {
            name: [] for name in fetch_names
        }
        out_cells: Dict[str, List] = (
            {}
            if dense_fast
            else {name: [None] * n for name in fetch_names}
        )
        from ..utils import get_config

        # buckets larger than the per-call row cap run in chunks: the input
        # bytes may be modest but the program's activations (convs,
        # attention) scale with the batch, so the cap bounds peak HBM
        chunk = max(1, get_config().max_rows_per_device_call)
        from ..utils import is_oom, run_with_retries

        def run_chunk(sub, sink=None):
            _m_blocks_map_rows.inc()
            idx_arr = np.asarray(sub, dtype=np.int64)
            contiguous = bool(
                idx_arr.size
                and idx_arr[-1] - idx_arr[0] + 1 == idx_arr.size
                and np.all(np.diff(idx_arr) == 1)
            )
            feed = {}
            for ph in binding:
                cd = col_data[ph]
                if cd.dense is not None:
                    h = cd.host()
                    feed[ph] = (
                        h[idx_arr[0] : idx_arr[-1] + 1]
                        if contiguous
                        else gather_rows(h, idx_arr)
                    )
                elif ph in ragged_bufs:
                    feed[ph] = ragged_bufs[ph].gather_pad(idx_arr)
                else:
                    feed[ph] = np.stack([cd.cell(i) for i in sub])
            def dispatch():
                import jax

                from ..utils.chaos import site as _chaos_site

                _chaos_site("engine.dispatch")
                # sync INSIDE the retry window: jax dispatch is async, so
                # without this the failure would surface at np.asarray
                # below, past the handlers. The chunk is materialized to
                # host right after anyway, so the sync costs nothing.
                return jax.block_until_ready(run_bucket(feed, len(sub)))

            try:
                if explicit_h2d:
                    # feeds cross the link through the streaming layer:
                    # each transfer chunk retried + counted + chaos-
                    # injectable; a dispatch retry below reuses the
                    # already-landed arrays. Inside THIS try so a device
                    # OOM during the upload halves the chunk like any
                    # other OOM (the recovery envelope must cover the
                    # feed bytes too, not just the program's activations)
                    feed = {
                        ph: _transfer.h2d(v, what="map_rows feed")
                        if isinstance(v, np.ndarray)
                        else v
                        for ph, v in feed.items()
                    }
                res = run_with_retries(dispatch, what="map_rows chunk")
            except Exception as e:
                # rows are independent, so an OOM chunk is safe to halve
                # (unlike a map_blocks partition); recurse down to 1 row
                if is_oom(e):
                    if len(sub) > 1:
                        record_oom_split("map_rows")
                        logger.warning(
                            "map_rows chunk of %d rows exhausted device "
                            "memory; halving", len(sub),
                        )
                        del feed
                        mid = len(sub) // 2
                        run_chunk(sub[:mid], sink)
                        run_chunk(sub[mid:], sink)
                        return
                    from ..utils.failures import DeviceOOMError

                    raise DeviceOOMError(
                        "map_rows row program exhausted device memory even "
                        "at one row per call; the per-row computation "
                        "itself does not fit HBM"
                    ) from e
                raise
            for name in fetch_names:
                arr = np.asarray(res[name])
                if sink is not None:
                    # journaled block execution collects per block (the
                    # halving recursion preserves row order) so the block's
                    # whole result can spool to the journal in one piece
                    sink(name, arr)
                elif dense_fast:
                    dense_pieces[name].append(arr)
                else:
                    for j, i in enumerate(sub):
                        out_cells[name][i] = arr[j]

        def _tuned_chunk(static_rows: int) -> int:
            """The block-row budget through the autotuner
            (``tensorframes_tpu.tune``, surface ``map_rows.block_rows``,
            keyed by per-row input bytes): Config's
            ``max_rows_per_device_call`` is the seed default; an online
            trial dispatches the REAL row program over a discarded
            sample at each candidate chunking (user-shaped, retryable,
            injectable at ``tune.trial`` like every other dispatch), so
            the winner reflects this op's actual dispatch-overhead/
            activation trade. Rows are independent and the halving
            recursion preserves row order, so every candidate is
            byte-identical to the default — the tuning contract."""
            from .. import tune

            if tune.mode() == "off":
                return static_rows
            if ledger is not None and not dense_fast:
                # bucketed (ragged) journal plans re-derive from the
                # live chunk on resume (no contiguous manifest rebuild),
                # so a tuned winner landing in the shared store between
                # a run and its resume would change the plan and fail
                # ensure_plan — ragged journaled jobs stay config-driven
                return static_rows
            per_row = 0
            for cd in col_data.values():
                if cd.dense is not None:
                    per_row += int(
                        np.prod(cd.dense.shape[1:], initial=1)
                    ) * cd.dense.dtype.itemsize
                elif cd.cells is not None and len(cd.cells):
                    c0 = np.asarray(cd.cells[0])
                    per_row += int(
                        np.prod(c0.shape, initial=1)
                    ) * c0.dtype.itemsize
            rb_bucket = 1 << max(2, int(max(per_row, 1) - 1).bit_length())
            # frame size is PART of the signature: candidates and trials
            # are n-dependent (a small frame cannot exercise a large
            # budget), so a winner measured at one scale must never
            # serve a job orders of magnitude bigger
            n_bucket = 1 << max(2, int(max(n, 1) - 1).bit_length())
            sig = (
                f"row_bytes={rb_bucket}|cols={len(col_data)}|n={n_bucket}"
            )
            default = {"rows": int(static_rows)}
            if dense_fast and n > 1:
                # the sample is the fixed workload every candidate
                # chunks; candidates past it would all measure as one
                # dispatch of `sample` rows — indistinguishable — so
                # only offer what the trial can genuinely compare. Two
                # candidates (one down, one up) + the default keep the
                # grid at 3, which the search measures IN FULL — with
                # only a dispatch-count ranking, a larger grid's
                # top-K halving would make the smaller-chunk side
                # structurally unreachable
                sample = int(min(n, static_rows * 2))
                cands = sorted(
                    {max(1, static_rows // 2), static_rows * 2}
                )
                grid = [
                    {"rows": int(c)}
                    for c in cands
                    if c != static_rows and 1 <= c <= sample
                ]

                def discard(name, arr):
                    pass

                def trial(cand):
                    rows = max(1, int(cand["rows"]))
                    lo = 0
                    while lo < sample:
                        hi = min(lo + rows, sample)
                        run_chunk(list(range(lo, hi)), sink=discard)
                        lo = hi

                def feats(cand):
                    rows = max(1, int(cand["rows"]))
                    dispatches = -(-sample // rows)
                    nbytes = float(sample * max(per_row, 1))
                    return 0.0, nbytes, float(dispatches)

            else:
                # ragged frames have no single contiguous bucket to
                # sample; they resolve cached-only (a winner tuned on a
                # matching dense signature still serves)
                grid, feats, trial = [], None, None
            try:
                win = tune.lookup(
                    "map_rows.block_rows", sig, default,
                    grid=grid, feats=feats, trial=trial,
                )
                return max(1, int(win.get("rows", static_rows)))
            except Exception:
                logger.warning(
                    "block-row tuning lookup failed; using "
                    "max_rows_per_device_call", exc_info=True,
                )
                return static_rows

        chunk = _tuned_chunk(chunk)

        def run_dense_fast() -> Optional[Dict[str, _ColumnData]]:
            """Device-resident execution for the all-dense single bucket:
            columns feed from memoized device copies (``_block_feeder``),
            chunks slice ON DEVICE and dispatch without per-chunk host
            syncs (each host round-trip costs ~40-100ms on a
            tunnel-attached TPU), and results concatenate on device — the
            same residency contract as ``map_blocks``. Returns ``None``
            when HBM would not stay bounded (streaming inputs, over-budget
            or unknown-size outputs) or on any runtime failure, in which
            case the synchronous chunked path (retry + OOM halving) runs
            instead."""
            import jax

            # EVERY bail-out runs before any feeder is built: building a
            # feeder for an in-budget host column STARTS its chunked
            # device upload, and bailing afterwards hands the pass to
            # run_chunk, which uploads the same bytes AGAIN per chunk —
            # the ROADMAP item-2 double-upload bug (an un-analyzed
            # frame's unknown out-spec dims always took that path).
            budget = get_config().device_cache_bytes
            est = 0
            for spec in out_specs.values():
                cell = spec.shape
                if any(d == Unknown for d in cell.dims):
                    return None
                est += (
                    int(np.prod(cell.dims)) if cell.dims else 1
                ) * spec.scalar_type.np_dtype.itemsize * n
            if est > budget:
                return None
            if any(_feeder_streams_host(col_data[ph]) for ph in binding):
                return None
            feeders = {
                ph: _block_feeder(col_data[ph])[0] for ph in binding
            }
            # small rows dispatch in larger chunks: the row cap protects
            # activation memory for heavy per-row programs, but each
            # dispatch pays link latency — scale the chunk up until a
            # call's input+output bytes reach the byte cap (1M scalar
            # rows: 123 row-capped dispatches -> 1)
            per_row = max(1, est // n)
            for ph in binding:
                cd = col_data[ph]
                cell = cd.dense.shape[1:]
                per_row += int(np.prod(cell, initial=1)) * cd.dense.dtype.itemsize
            byte_capped = max(
                chunk, int(get_config().max_bytes_per_device_call // per_row)
            )

            reached_cap = [byte_capped <= chunk]

            def attempt(fast_chunk):
                """One device-resident pass at the given starting chunk.
                The first chunk at each raised size syncs as an OOM probe
                (halving toward the row cap); later same-size chunks
                dispatch async. A late async OOM (memory pressure grows as
                result pieces accumulate) surfaces at the terminal sync
                and is handled by the caller's row-cap retry — unless this
                pass already ran at the cap (``reached_cap``), where a
                repeat would just OOM again."""
                pieces: Dict[str, List] = {name: [] for name in fetch_names}
                lo = 0
                probe_size = fast_chunk if fast_chunk > chunk else None
                from ..utils.chaos import site as _chaos_site

                while lo < n:
                    hi = min(lo + fast_chunk, n)
                    _m_blocks_map_rows.inc()
                    feed = {ph: feeders[ph](lo, hi) for ph in binding}
                    try:
                        # chaos here exercises the degrade path: a
                        # non-OOM failure drops the whole pass to the
                        # synchronous chunked engine (retry + halving)
                        _chaos_site("engine.dispatch")
                        res = run_bucket(feed, hi - lo)
                        # the raised-chunk OOM probe syncs so halving can
                        # react before the rest of the pass dispatches —
                        # pointless when this chunk IS the whole pass (the
                        # terminal sync right below catches it, and the
                        # caller's row-cap retry recovers); skipping it
                        # saves one ~100-200ms tunnel round trip per
                        # single-chunk pass (the r04 config7 gap)
                        if probe_size == fast_chunk and hi < n:
                            jax.block_until_ready(res)
                            probe_size = None
                    except Exception as e:
                        if is_oom(e) and fast_chunk > chunk:
                            record_oom_split("map_rows")
                            fast_chunk = max(chunk, fast_chunk // 2)
                            if fast_chunk <= chunk:
                                reached_cap[0] = True
                            probe_size = (
                                fast_chunk if fast_chunk > chunk else None
                            )
                            logger.warning(
                                "map_rows raised chunk exhausted device "
                                "memory; lowering to %d rows", fast_chunk,
                            )
                            del feed
                            continue
                        raise
                    for name in fetch_names:
                        pieces[name].append(res[name])
                    lo = hi
                cols: Dict[str, _ColumnData] = {}
                for name in fetch_names:
                    # sync (no transfer) so async failures surface in this
                    # window, not later in user code
                    arr = jax.block_until_ready(
                        _concat_dense(pieces[name])
                    )
                    cols[name] = _ColumnData(dense=arr)
                return cols

            try:
                return attempt(byte_capped)
            except Exception as e:
                if is_oom(e) and not reached_cap[0]:
                    # a LATER raised chunk OOMed past the probe: retry the
                    # whole pass at the row cap, keeping device residency
                    # (skipped when the pass already halved to the cap and
                    # still OOMed — a repeat would fail the same way)
                    record_oom_split("map_rows")
                    logger.warning(
                        "map_rows byte-capped pass exhausted device "
                        "memory past the probe; retrying device-resident "
                        "at the %d-row cap", chunk,
                    )
                    try:
                        return attempt(chunk)
                    except Exception:
                        pass
                logger.warning(
                    "map_rows device-resident path failed; falling back "
                    "to synchronous chunked execution",
                    exc_info=True,
                )
                return None

        dropped_rows: List[int] = []
        cols = (
            run_dense_fast()
            if dense_fast and device_resident and ledger is None
            else None
        )
        if cols is None:
            if ledger is not None:
                # -- journaled block loop (engine/jobs.py) -----------------
                if dense_fast:
                    # resume: rebuild the SAME plan the journal was
                    # written with (contiguous row ranges straight off
                    # the manifest) — knobs that shape FRESH plans may
                    # have been retuned since, and a resume must restore
                    # completed blocks, not reject them over a config
                    # delta. The fingerprint still validates everything
                    # else, and ensure_plan re-checks entry equality.
                    plan_subs: Optional[List[Sequence[int]]] = None
                    stored = ledger.stored_plan
                    if stored:
                        subs: List[Sequence[int]] = []
                        nxt = 0
                        for e in stored:
                            first, last = e.get("first"), e.get("last")
                            if (
                                first != nxt
                                or last is None
                                or e.get("rows") != last - first + 1
                            ):
                                subs = None  # bucketed/foreign plan
                                break
                            subs.append(range(first, last + 1))
                            nxt = last + 1
                        if subs is not None and nxt == n:
                            plan_subs = subs
                    if plan_subs is None:
                        # fresh job: the plan chunk is CAPPED at the
                        # transfer-chunk row quantum so a journal block
                        # never spans transfer chunks — a resumed job
                        # re-uploads exactly its unfinished blocks'
                        # bytes and nothing of the completed ones
                        # (docs/ingest.md)
                        per_row_bytes = sum(
                            _transfer.wire_dtype(cd.dense.dtype).itemsize
                            * int(np.prod(cd.dense.shape[1:], initial=1))
                            for cd in col_data.values()
                            if cd.dense is not None
                        )
                        plan_chunk = max(
                            1,
                            min(chunk, _transfer.chunk_rows(per_row_bytes)),
                        )
                        plan_subs = [
                            range(lo, min(lo + plan_chunk, n))
                            for lo in range(0, n, plan_chunk)
                        ]
                else:
                    plan_subs = [
                        idxs[lo : lo + chunk]
                        for _, idxs in buckets.items()
                        for lo in range(0, len(idxs), chunk)
                    ]

                def plan_entry(sub):
                    first, last = int(sub[0]), int(sub[-1])
                    if isinstance(sub, range):
                        total = (first + last) * len(sub) // 2
                    else:
                        total = int(
                            np.asarray(sub, dtype=np.int64).sum()
                        )
                    return {
                        "rows": len(sub),
                        "first": first,
                        "last": last,
                        "ck": int(total % (1 << 31)),
                    }

                ledger.ensure_plan(
                    [plan_entry(s) for s in plan_subs],
                    graph=graph, schema=parent.schema, rows=n,
                )
                for bi, sub in enumerate(plan_subs):
                    st, arrs = ledger.lookup(bi)
                    if st == "quarantined":
                        dropped_rows.extend(int(i) for i in sub)
                        continue
                    if st == "todo":
                        def compute(sub=sub):
                            acc: Dict[str, List[np.ndarray]] = {
                                name: [] for name in fetch_names
                            }
                            run_chunk(
                                sub,
                                sink=lambda name, arr: acc[name].append(arr),
                            )
                            return {
                                name: (
                                    np.concatenate(acc[name], axis=0)
                                    if len(acc[name]) > 1
                                    else acc[name][0]
                                )
                                for name in fetch_names
                            }

                        arrs = ledger.run_block(bi, compute, rows=len(sub))
                        if arrs is None:  # quarantined just now
                            dropped_rows.extend(int(i) for i in sub)
                            continue
                    for name in fetch_names:
                        arr = arrs[name]
                        if dense_fast:
                            dense_pieces[name].append(arr)
                        else:
                            for j, i in enumerate(sub):
                                out_cells[name][i] = arr[j]
            else:
                if dense_fast and not buckets:
                    buckets[tuple(dense_keys[ph] for ph in binding)] = list(
                        range(n)
                    )
                for _, idxs in buckets.items():
                    for lo in range(0, len(idxs), chunk):
                        run_chunk(idxs[lo : lo + chunk])
            cols = {}
            dropped_set = set(dropped_rows)
            if dense_fast:
                for name in fetch_names:
                    ps = dense_pieces[name]
                    if not ps:
                        dense = _empty_output(
                            out_specs[name], block_output=False
                        )
                    else:
                        dense = _concat_dense(ps)
                    cols[name] = _ColumnData(dense=dense)
            elif dropped_set:
                for name in fetch_names:
                    cd, _ = _build_column(
                        name,
                        [
                            out_cells[name][i]
                            for i in range(n)
                            if i not in dropped_set
                        ],
                    )
                    cols[name] = cd
            else:
                for name in fetch_names:
                    cd, _ = _build_column(name, out_cells[name])
                    cols[name] = cd
        if dropped_rows:
            # quarantined blocks' rows vanish from the result: carried
            # parent columns take the survivors, and partition offsets
            # shrink by each partition's dropped count
            dropped_arr = np.asarray(sorted(dropped_rows), dtype=np.int64)
            keep = np.setdiff1d(
                np.arange(n, dtype=np.int64), dropped_arr,
                assume_unique=True,
            )
            for c in parent.schema:
                cols[c.name] = parent.column_data(c.name).take(keep)
            part_counts = [
                int(hi - lo)
                - int(np.searchsorted(dropped_arr, hi)
                      - np.searchsorted(dropped_arr, lo))
                for lo, hi in parent.partition_bounds()
            ]
            offsets = np.concatenate(
                [[0], np.cumsum(part_counts)]
            ).astype(np.int64)
            return TensorFrame(cols, result_info, offsets=offsets)
        for c in parent.schema:
            cols[c.name] = parent.column_data(c.name)
        if result_partitions is not None:
            return TensorFrame(
                cols, result_info, num_partitions=result_partitions
            )
        offsets = np.array(
            [lo for lo, _ in parent.partition_bounds()] + [n], dtype=np.int64
        )
        return TensorFrame(cols, result_info, offsets=offsets)

    def instrumented() -> TensorFrame:
        with _span("engine.map_rows") as sp:
            out = thunk()
            if sp is not None:
                sp.attrs["rows"] = parent.num_rows
        _m_rows_map_rows.inc(parent.num_rows)
        return out

    return instrumented


def apply_decoders(
    dframe: TensorFrame,
    decoders: Dict[str, Callable],
    feed_dict: Optional[Dict[str, str]] = None,
) -> TensorFrame:
    """Stack host decode stages onto a frame (see
    :meth:`TensorFrame.decode_column`). Keys are column names, or
    placeholder names routed through ``feed_dict`` — matching how the
    reference binds its string tensor to the bytes column
    (``read_image.py:158-160``). Decoding is forced here and the result
    ``analyze``d so downstream capture sees concrete cell shapes (the
    reference likewise requires ``tfs.analyze`` before non-scalar ops)."""
    for col, fn in _resolve_decoder_cols(
        decoders, feed_dict, dframe.schema.names
    ).items():
        dframe = dframe.decode_column(col, fn)
    return dframe.analyze()


def map_rows(
    fetches,
    dframe: TensorFrame,
    feed_dict: Optional[Dict[str, str]] = None,
    decoders: Optional[Dict[str, Callable]] = None,
    _ledger=None,
    _plan: bool = True,
) -> TensorFrame:
    """Transform row by row (``core.py:223-264``). Rows with equal cell
    shapes are batched and executed with ``vmap`` in one XLA program per
    shape bucket — the TPU replacement for the reference's one-Session.run-
    per-row loop (``performMapRows``, ``DebugRowOps.scala:819-857``). Ragged
    columns are supported; binary columns run on the host path — or, with
    ``decoders={placeholder_or_column: bytes -> array}``, decode on the
    host and batch the numeric program on device (the reference's
    decode-in-graph image scoring, ``read_image.py:147-167``, done the
    TPU way)."""
    if decoders:
        dframe = apply_decoders(dframe, decoders, feed_dict)
    g = _as_graph(fetches, dframe, cell_inputs=True, feed_dict=feed_dict)
    binding = validate_map_inputs(g, dframe.schema, block=False)
    _ensure_precision(g, dframe.schema)
    host_mode = any(
        dframe.schema[col].scalar_type.name == "binary"
        for col in binding.values()
    )
    if host_mode and _ledger is not None:
        raise ValueError(
            "journaled map_rows does not support binary-column host "
            "programs; decode to numeric columns first (decoders=) and "
            "journal the numeric pass"
        )
    if host_mode:
        # binary programs run on the host; discover output specs from a real
        # first-row execution (the reference analyzes binary graphs via the
        # TF runtime — there is no abstract trace for host programs here)
        if dframe.num_rows == 0:
            raise ValueError("map_rows on an empty binary-column frame")
        from ..schema import for_any

        probe = g.fn(
            {ph: dframe.column_data(col).cell(0) for ph, col in binding.items()}
        )
        out_specs = {
            name: TensorSpec(
                name,
                for_any(np.asarray(v) if not isinstance(v, bytes) else v),
                Shape([Unknown] * np.asarray(v).ndim)
                if not isinstance(v, bytes)
                else Shape.empty(),
            )
            for name, v in probe.items()
            if name in g.fetch_names
        }
    else:
        input_shapes = {
            ph: dframe.schema[col].cell_shape for ph, col in binding.items()
        }
        out_specs = g.analyze(input_shapes, share_lead=False)
    check_output_collisions(out_specs, dframe.schema)
    fetch_names = sorted(out_specs)
    fetch_infos = [
        _fetch_column_info(n, out_specs[n], block_output=False)
        for n in fetch_names
    ]
    result_info = FrameInfo(fetch_infos + list(dframe.schema))
    parent = dframe

    if host_mode:

        def thunk() -> TensorFrame:
            n = parent.num_rows
            if n == 0:
                cols = {
                    name: _ColumnData(
                        dense=_empty_output(
                            out_specs[name], block_output=False
                        )
                    )
                    for name in fetch_names
                }
                for c in parent.schema:
                    cols[c.name] = parent.column_data(c.name)
                return TensorFrame(cols, result_info)
            col_data = {
                ph: parent.column_data(col) for ph, col in binding.items()
            }
            out_cells: Dict[str, List] = {
                name: [None] * n for name in fetch_names
            }
            for i in range(n):
                feed = {ph: cd.cell(i) for ph, cd in col_data.items()}
                res = g.fn(feed)
                for name in fetch_names:
                    v = res[name]
                    out_cells[name][i] = (
                        v
                        if isinstance(v, (bytes, bytearray))
                        else np.asarray(v)
                    )
            cols: Dict[str, _ColumnData] = {}
            for name in fetch_names:
                cd, _ = _build_column(name, out_cells[name])
                cols[name] = cd
            for c in parent.schema:
                cols[c.name] = parent.column_data(c.name)
            offsets = np.array(
                [lo for lo, _ in parent.partition_bounds()] + [n],
                dtype=np.int64,
            )
            return TensorFrame(cols, result_info, offsets=offsets)

        _host_run = thunk

        def thunk() -> TensorFrame:
            with _span("engine.map_rows", host=True) as sp:
                out = _host_run()
                if sp is not None:
                    sp.attrs["rows"] = parent.num_rows
            _m_rows.inc(parent.num_rows, op="map_rows_host")
            return out

    else:
        thunk = _map_rows_thunk(
            parent,
            binding,
            fetch_names,
            out_specs,
            result_info,
            run_bucket=lambda feed, m: _jitted_vmap(g)(feed),
            ledger=_ledger,
            graph=g,
            explicit_h2d=True,
        )

    if _plan and _ledger is None and not host_mode:
        from . import plan as _plan_mod

        if _plan_mod.enabled():
            # logical-plan node (docs/pipelines.md); binary/host-path
            # programs stay op-at-a-time and bound the chain
            return _plan_mod.make_lazy_map(
                "map_rows", parent, g, binding, fetch_names,
                result_info, thunk,
            )
    return TensorFrame(
        {}, result_info, num_partitions=parent.num_partitions, _thunk=thunk
    )


# ---------------------------------------------------------------------------
# reduce_blocks / reduce_rows
# ---------------------------------------------------------------------------


def _unpack_reduce_result(
    acc: Dict[str, Any], fetch_names: Sequence[str]
) -> Union[np.ndarray, List[np.ndarray]]:
    """Reference ``_unpack_row`` (``core.py:110-124``): numpy per fetch,
    unwrapped when there is a single fetch. One batched device_get for all
    fetches — per-fetch np.asarray would pay one host round-trip each."""
    import jax

    host = jax.device_get({f: acc[f] for f in fetch_names})
    vals = []
    for f in fetch_names:
        a = np.asarray(host[f])
        vals.append(a if a.ndim > 0 else a[()])
    return vals[0] if len(vals) == 1 else vals


def reduce_blocks(fetches, dframe: TensorFrame, _ledger=None):
    """Block reduce to a single row (eager; ``core.py:311-349``). One program
    run per partition block, then a fixed ``[2, ...]`` merge program folds
    the partials — replacing the reference's executors→driver funnel
    (``DebugRowOps.scala:503-526``).

    ``_ledger`` (private) is the durable-job hook (``engine/jobs.py``):
    per-partition partials spool to the journal, quarantined partitions
    drop out of the fold, and a resume folds restored + freshly-computed
    partials in partition order (byte-identical to a clean run). Returns
    ``None`` when a journaled job quarantined every partition.

    Over a *pending planned* frame (a recorded map chain that has not
    been forced) this is a plan terminal: with
    ``Config.plan_hoist_reduce`` the reduce folds into the fused map
    program's per-block epilogue, and either way the reduce's bindings
    drive column pruning — the chain's dead ops never run and their
    source columns never cross the link (``engine/plan.py``)."""
    with _span("engine.reduce_blocks", partitions=dframe.num_partitions):
        from . import plan as _plan_mod

        handled, out, rows = (False, None, None)
        if _plan_mod.enabled():
            handled, out, rows = _plan_mod.reduce_terminal(
                fetches, dframe, ledger=_ledger
            )
        if not handled:
            out = _reduce_blocks_impl(fetches, dframe, _ledger)
            rows = dframe.num_rows
    _m_rows.inc(rows, op="reduce_blocks")
    return out


def _reduce_blocks_impl(fetches, dframe: TensorFrame, ledger=None):
    # NOTE: engine/plan.py's `_lower_hoisted_reduce` mirrors this drive
    # (grouped async dispatch unjournaled, per-partition sync + spool
    # journaled, OOM degrade to halved spans merged through the reduce
    # program) with a fused maps+reduce partial program — a semantics
    # change to retry/OOM/quarantine handling here must be applied there
    g = _as_graph(fetches, dframe, cell_inputs=False)
    binding = validate_reduce_block_graph(g, dframe.schema)
    _ensure_precision(g, dframe.schema)
    jit_fn = _jitted(g)
    feeders = {}
    any_streams = False
    for f, col in binding.items():
        dframe.column_block(col, None)  # rejects ragged/binary
        feeders[f], streams = _block_feeder(dframe.column_data(col))
        any_streams = any_streams or streams
    import jax.numpy as jnp

    from ..utils import is_oom, run_with_retries

    bounds = dframe.partition_bounds()

    def merge_two(a, b):
        feed = {
            f"{f}_input": jnp.stack([a[f], b[f]]) for f in binding
        }
        return jit_fn(feed)

    def partial_for_span(lo: int, hi: int, what: str):
        """One partial over rows [lo, hi) — with OOM degrade: a span too
        large for HBM halves recursively and the halves merge through the
        same ``[2, ...]`` program the partition fold uses. Sound for the
        same reason the fold is: reduce_blocks programs are declared
        algebraic over blocks (``Operations.scala:110-120``)."""
        feed = {f"{f}_input": feeders[f](lo, hi) for f in binding}

        def dispatch():
            import jax

            from ..utils.chaos import site as _chaos_site

            _chaos_site("engine.dispatch")
            # sync INSIDE the retry window (partials are consumed by the
            # host-driven fold right after, so the sync costs nothing)
            return jax.block_until_ready(jit_fn(feed))

        try:
            return run_with_retries(dispatch, what=what)
        except Exception as e:
            if is_oom(e):
                if hi - lo > 1:
                    record_oom_split("reduce_blocks")
                    logger.warning(
                        "reduce_blocks span of %d rows exhausted device "
                        "memory; halving and merging the halves",
                        hi - lo,
                    )
                    del feed
                    mid = (lo + hi) // 2
                    a = partial_for_span(lo, mid, what)
                    b = partial_for_span(mid, hi, what)
                    return merge_two(a, b)
                from ..utils.failures import DeviceOOMError

                raise DeviceOOMError(
                    "reduce_blocks partial exhausted device memory even at "
                    "a single row; the per-block reduce itself does not "
                    "fit HBM"
                ) from e
            raise

    if ledger is not None:
        ledger.ensure_plan(
            [{"rows": hi - lo, "lo": lo, "hi": hi} for lo, hi in bounds],
            graph=g, schema=dframe.schema, rows=dframe.num_rows,
        )
    partials: List[Dict[str, Any]] = []
    if ledger is not None or any_streams:
        # per-partition dispatch with a sync each: journaled jobs need
        # host partials to spool (and per-block failure isolation); a
        # streaming column bounds HBM at one block's buffers. A transient
        # failure retries only its own partition, an OOM halves it.
        for p, (lo, hi) in enumerate(bounds):
            if hi == lo:
                continue
            what = f"reduce_blocks partition {p}"
            if ledger is not None:
                st, arrs = ledger.lookup(p)
                if st == "quarantined":
                    continue
                if st == "done":
                    partials.append(arrs)
                    continue
                res = ledger.run_block(
                    p,
                    lambda lo=lo, hi=hi, what=what: {
                        f: np.asarray(v)
                        for f, v in partial_for_span(lo, hi, what).items()
                    },
                    rows=hi - lo,
                )
                if res is not None:
                    partials.append(res)
            else:
                partials.append(partial_for_span(lo, hi, what))
    else:

        def feed_for(p):
            lo, hi = bounds[p]
            if hi - lo == 0:
                return None
            return {f"{f}_input": feeders[f](lo, hi) for f in binding}

        def all_partials() -> List[Dict[str, Any]]:
            import jax

            from ..utils.chaos import site as _chaos_site

            _chaos_site("engine.dispatch")
            ps = [
                jit_fn(feed)
                for feed in map(feed_for, range(dframe.num_partitions))
                if feed is not None
            ]
            # device-cached feeds: dispatch every partition async, ONE sync
            # for the group inside the retry window (per-partition syncing
            # costs one host round-trip per partition; a group retry only
            # re-runs compute, the transfers are memoized)
            return jax.block_until_ready(ps)

        try:
            partials = run_with_retries(
                all_partials, what="reduce_blocks partials"
            )
        except Exception as e:
            if not is_oom(e):
                raise
            # a partial blew HBM inside the grouped async dispatch: fall
            # back to the sequential per-partition path, where an
            # oversized span halves and its halves merge (the map_rows
            # degrade contract, brought to the reduce partials path)
            logger.warning(
                "reduce_blocks grouped dispatch exhausted device memory; "
                "retrying per partition with OOM halving",
            )
            partials = [
                partial_for_span(lo, hi, f"reduce_blocks partition {p}")
                for p, (lo, hi) in enumerate(bounds)
                if hi > lo
            ]
    if not partials:
        if ledger is not None and ledger.quarantined_indices:
            return None  # every partition quarantined; jobs.py surfaces it
        raise ValueError("reduce_blocks on an empty frame")
    _m_blocks.inc(len(partials), op="reduce_blocks")
    acc = partials[0]
    for part in partials[1:]:
        acc = merge_two(acc, part)
    return _unpack_reduce_result(acc, g.fetch_names)


def reduce_rows(fetches, dframe: TensorFrame):
    """Pairwise row reduce (eager; ``core.py:184-221``): fetch ``x`` consumes
    placeholders ``x_1``/``x_2``. Within a partition the fold is a
    ``lax.scan`` over the block (the reference's sequential
    ``performReducePairwise``, ``DebugRowOps.scala:930-969``, with the
    session loop compiled away); across partitions the same merge program
    folds the partials."""
    with _span("engine.reduce_rows", partitions=dframe.num_partitions):
        out = _reduce_rows_impl(fetches, dframe)
    _m_rows.inc(dframe.num_rows, op="reduce_rows")
    return out


def _reduce_rows_impl(fetches, dframe: TensorFrame):
    g = _as_graph(fetches, dframe, cell_inputs=True)
    binding = validate_reduce_row_graph(g, dframe.schema)
    _ensure_precision(g, dframe.schema)
    import jax
    import jax.numpy as jnp
    from jax import lax

    fetch_names = list(g.fetch_names)

    def merge(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
        feed = {}
        for f in fetch_names:
            feed[f"{f}_1"] = a[f]
            feed[f"{f}_2"] = b[f]
        return g.fn(feed)

    fold_block = getattr(g, "_fold_block_cache", None)
    if fold_block is None:

        @jax.jit
        def fold_block(block_feed: Dict[str, Any]) -> Dict[str, Any]:
            init = {f: block_feed[f][0] for f in fetch_names}
            rest = {f: block_feed[f][1:] for f in fetch_names}

            def body(carry, xs):
                return merge(carry, xs), None

            out, _ = lax.scan(body, init, rest)
            return out

        g._fold_block_cache = fold_block

    merge_jit = getattr(g, "_merge_cache", None)
    if merge_jit is None:
        merge_jit = jax.jit(merge)
        g._merge_cache = merge_jit

    feeders = {}
    for f, col in binding.items():
        dframe.column_block(col, None)  # rejects ragged/binary
        feeders[f], _ = _block_feeder(dframe.column_data(col))
    partials: List[Dict[str, Any]] = []
    for p in range(dframe.num_partitions):
        lo, hi = dframe.partition_bounds()[p]
        if hi - lo == 0:
            continue
        feed = {f: feeders[f](lo, hi) for f in binding}
        partials.append(fold_block(feed))
    if not partials:
        raise ValueError("reduce_rows on an empty frame")
    _m_blocks.inc(len(partials), op="reduce_rows")
    acc = partials[0]
    for part in partials[1:]:
        acc = merge_jit(acc, part)
    return _unpack_reduce_result(acc, fetch_names)


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------

#: rows per chunk in the large-frame aggregate path: the segmented scan's
#: compile time grows with log2(rows scanned), so large frames are scanned
#: as [m, _AGG_CHUNK] with vmap (fixed depth, one compile per cell shape)
#: and per-chunk boundary partials merged by a recursive final pass
_AGG_CHUNK = 32768


def _group_sort(dframe: TensorFrame, keys: Sequence[str], binding) -> Tuple:
    """Memoizing wrapper around :func:`_group_sort_impl`: frames are
    immutable, so the sort permutation for a given key tuple is computed
    once per frame — repeated aggregates over the same grouping (different
    fetches, iterative passes) skip the sort and its host sync entirely."""
    cache = getattr(dframe, "_group_sort_cache", None)
    if cache is None:
        cache = dframe._group_sort_cache = {}
    ck = tuple(keys)
    hit = cache.get(ck)
    if hit is None:
        hit = cache[ck] = _group_sort_impl(dframe, keys, binding)
    else:
        # the binding checks in the impl are per-call (key/input overlap)
        for k in keys:
            if k in binding.values():
                raise ValueError(
                    f"column {k!r} cannot be both key and input"
                )
    return hit


def _segment_flags(neq, n: int) -> np.ndarray:
    """Host bool segment-start marks from a DEVICE adjacent-inequality
    vector, without the O(n) readback: one scalar (distinct-boundary
    count) and one [G-1] index vector cross the link instead of n bools.
    The host array is reconstructed by scattering True at the starts —
    grouped aggregation's host<->device traffic becomes O(groups) in the
    many-rows-per-group regime, which is what makes 10M-row aggregates
    usable on a tunnel-attached chip. High-cardinality keys (groups ~
    rows) fall back to the plain bool readback, which is the smaller
    transfer there."""
    import jax.numpy as jnp

    g_minus_1 = int(neq.sum())
    if g_minus_1 > max(n // 8, 1):
        return np.concatenate([[True], np.asarray(neq)])
    flags = np.zeros(n, dtype=bool)
    flags[0] = True
    if g_minus_1:
        # round the static nonzero size up to a power of two so a stream
        # of frames with varying group counts compiles O(log n) programs,
        # not one per distinct count; fill_value=-1 marks the padding
        # (a real boundary index can be 0)
        size = 1 << (g_minus_1 - 1).bit_length()
        starts = np.asarray(
            jnp.nonzero(neq, size=size, fill_value=-1)[0]
        )
        starts = starts[:g_minus_1] + 1
        flags[starts] = True
    return flags


def _group_sort_impl(dframe: TensorFrame, keys: Sequence[str], binding) -> Tuple:
    """Group-key machinery shared by the local and distributed aggregates.

    Supports numeric scalar keys, binary (bytes/string) keys, and
    multi-column combinations of both — the reference aggregates under any
    Spark ``groupBy`` key, strings included (``DebugRowOps.scala:547-592``,
    ``core_test.py:213-222``).

    The sort itself runs ON DEVICE (stable argsort over the key column, or
    over host-computed integer codes), so host work is at most the O(n)
    dict-coding pass for binary/multi keys; for a single numeric key the
    host does no per-row work at all.

    Returns ``(order, flags, emit_keys)``:

    - ``order``: DEVICE int row permutation grouping equal keys (stays on
      device — for large frames it is tens of MB that the feed gather
      consumes in HBM anyway),
    - ``flags``: host bool segment-start marks over the sorted rows,
    - ``emit_keys(ends) -> dict[name, column_data]``: the representative key
      value per group, given sorted-row segment-end indices.
    """
    import jax.numpy as jnp

    n = dframe.num_rows
    key_cds = []
    for k in keys:
        kd = dframe.column_data(k)
        if kd.dense is None and not kd.is_binary:
            raise ValueError(
                f"grouping column {k!r} is ragged; group keys must be "
                f"scalars or binary cells"
            )
        if kd.dense is not None and kd.dense.ndim != 1:
            raise ValueError(
                f"grouping column {k!r} must hold scalar cells to group by"
            )
        if k in binding.values():
            raise ValueError(f"column {k!r} cannot be both key and input")
        key_cds.append(kd)

    if all(kd.dense is not None for kd in key_cds):
        # pure numeric: device lexsort via repeated stable argsort
        # (last key first), flags from adjacent inequality on device
        order_dev = None
        for kd in reversed(key_cds):
            kv = kd.device()
            if order_dev is None:
                order_dev = jnp.argsort(kv, stable=True)
            else:
                order_dev = order_dev[
                    jnp.argsort(kv[order_dev], stable=True)
                ]
        sorted_keys = [kd.device()[order_dev] for kd in key_cds]
        neq = None
        for sk in sorted_keys:
            d = sk[1:] != sk[:-1]
            neq = d if neq is None else (neq | d)
        flags = _segment_flags(neq, n)
        order = order_dev  # device-resident; no host round trip

        def emit_keys(ends):
            ends_dev = jnp.asarray(np.asarray(ends))
            return {
                k: sk[ends_dev] for k, sk in zip(keys, sorted_keys)
            }

    else:
        # binary or mixed keys: integer codes by first appearance.
        # pandas' hash-based ``factorize`` does this at C speed with no
        # sort and native first-appearance ordering (measured 0.7s for 10M
        # bytes keys, vs ~35s for a fixed-width-S np.unique sort and ~10s
        # for a per-row dict loop); a numpy np.unique path (provisional
        # codes -> first-appearance renumber) is the no-pandas fallback.
        # The sort over codes still runs on device.
        try:
            import pandas as pd
        except Exception:  # pragma: no cover - pandas is a std dep here
            pd = None

        def first_appearance_codes(arr, axis=None):
            _, first, inv = np.unique(
                arr, axis=axis, return_index=True, return_inverse=True
            )
            rank = np.empty(len(first), dtype=np.int64)
            rank[np.argsort(first, kind="stable")] = np.arange(len(first))
            return rank[inv.reshape(-1)]

        def binary_codes(cells) -> np.ndarray:
            # fastest path: the native thread-pool coder (parallel local
            # dictionaries + first-appearance merge, executor.cpp); it
            # returns None without the compiled library or on non-bytes
            # cells, falling through to pandas/numpy
            from ..data.packer import code_keys

            native = code_keys(cells)
            if native is not None:
                return native.astype(np.int64, copy=False)
            if pd is not None:
                arr = np.empty(n, dtype=object)
                # storage cells are bytes already: direct elementwise
                # assign (C speed) instead of 10M bytes() calls. The
                # TypeError fallback covers non-bytes byte-likes
                # (bytearray, memoryview), which assign fine but are
                # unhashable inside factorize; genuine factorize failures
                # (MemoryError etc.) propagate.
                arr[:] = cells
                try:
                    return pd.factorize(arr)[0].astype(np.int64, copy=False)
                except TypeError:
                    arr[:] = [bytes(c) for c in cells]
                    return pd.factorize(arr)[0].astype(np.int64, copy=False)
            # fallback: fixed-width S array (trailing 0x01 sentinel defeats
            # numpy's trailing-NUL stripping) unless one outlier key would
            # balloon the n x max_len buffer, where the O(total bytes)
            # dict loop is the cheaper pass
            lengths = np.fromiter(
                (len(c) for c in cells), dtype=np.int64, count=n
            )
            padded = n * (int(lengths.max(initial=0)) + 1)
            total = int(lengths.sum()) + n
            if padded > max(total * 8, 1 << 26):
                mapping: Dict[bytes, int] = {}
                out = np.empty(n, dtype=np.int64)
                for i, c in enumerate(cells):
                    c = bytes(c)
                    code = mapping.get(c)
                    if code is None:
                        code = mapping[c] = len(mapping)
                    out[i] = code
                return out
            arr = np.asarray([bytes(c) + b"\x01" for c in cells])
            _, inv = np.unique(arr, return_inverse=True)
            inexact_order.append(True)  # unique sorts; not first-appearance
            return inv.reshape(-1).astype(np.int64)

        #: coders append here when their output is NOT first-appearance
        #: ordered (numpy unique fallbacks sort; the NaN branch appends
        #: singletons at the end of the range); a single-column result
        #: then gets one renumber pass, exact coders skip it
        inexact_order = []

        def numeric_codes(vals: np.ndarray) -> np.ndarray:
            # NaN semantics must match the dense-numeric path and the old
            # dict loop: NaN != NaN, so every NaN row is its own group
            # (factorize/np.unique would collapse or sentinel them)
            if np.issubdtype(vals.dtype, np.floating):
                nan = np.isnan(vals)
                if nan.any():
                    inexact_order.append(True)
                    out = np.empty(n, dtype=np.int64)
                    nn = vals[~nan]
                    if pd is not None:
                        out[~nan] = pd.factorize(nn)[0]
                    else:
                        _, inv = np.unique(nn, return_inverse=True)
                        out[~nan] = inv.reshape(-1)
                    k = n - int(nan.sum())
                    out[nan] = k + np.arange(int(nan.sum()))
                    return out
            if pd is not None:
                return pd.factorize(vals)[0].astype(np.int64, copy=False)
            _, inv = np.unique(vals, return_inverse=True)
            inexact_order.append(True)  # unique sorts; not first-appearance
            return inv.reshape(-1).astype(np.int64)

        per_col = [
            binary_codes(kd.cells) if kd.is_binary else numeric_codes(kd.host())
            for kd in key_cds
        ]
        if pd is not None:
            codes = per_col[0]
            for nxt in per_col[1:]:
                # re-factorize after each pairwise combine so the running
                # code range stays < n and the product cannot overflow;
                # factorize output is first-appearance, so combined codes
                # need no extra renumber
                codes = pd.factorize(
                    codes * (np.int64(nxt.max(initial=0)) + 1) + nxt
                )[0]
            if len(per_col) == 1 and inexact_order:
                # the one non-first-appearance coder: NaN singleton rows
                # appended at the end of the range
                codes = pd.factorize(codes)[0]
            codes = codes.astype(np.int64, copy=False)
        elif len(per_col) == 1:
            codes = (
                first_appearance_codes(per_col[0])
                if inexact_order
                else per_col[0]
            )
        else:
            codes = first_appearance_codes(
                np.stack(per_col, axis=1), axis=0
            )
        # codes are group ids < n: the narrowest dtype cuts the one
        # unavoidable link transfer of the string-key path (the codes
        # upload; order/flags already stay device-side) by 2-4x
        mx = int(codes.max()) if codes.size else 0
        if mx < (1 << 8):
            codes = codes.astype(np.uint8)
        elif mx < (1 << 16):
            codes = codes.astype(np.uint16)
        elif n < 2**31:
            codes = codes.astype(np.int32, copy=False)
        codes_dev = jnp.asarray(codes)
        order_dev = jnp.argsort(codes_dev, stable=True)
        sorted_c = codes_dev[order_dev]
        flags = _segment_flags(sorted_c[1:] != sorted_c[:-1], n)
        order = order_dev  # device-resident, same as the numeric path

        def emit_keys(ends):
            # gather the G representative row indices ON DEVICE and pull
            # only those (the full permutation never crosses the link)
            ends_dev = jnp.asarray(np.asarray(ends))
            rows = np.asarray(order_dev[ends_dev])
            out = {}
            for k, kd in zip(keys, key_cds):
                if kd.is_binary:
                    out[k] = [kd.cells[i] for i in rows]
                else:
                    out[k] = kd.host()[rows]
            return out

    return order, flags, emit_keys


def aggregate(fetches, grouped_data: GroupedFrame) -> TensorFrame:
    """Keyed algebraic aggregation (``core.py:377-395``): for grouped data,
    reduce each group with the block-reduce graph.

    TPU-native design replacing the reference's Spark-shuffle UDAF
    (``TensorFlowUDAF``, ``DebugRowOps.scala:601-695``):

    1. per-row partials: the reduce graph runs on blocks of 1 via ``vmap``
       (one program, any row count);
    2. rows sorted by group key ON DEVICE (stable argsort; binary/mixed
       keys get O(n) host dict-coding first — see :func:`_group_sort`);
    3. one *segmented associative scan* on device combines partials within
       segments — ``combine((a,fa),(b,fb)) = (fb ? b : merge(a,b), fa|fb)``
       where ``merge`` stacks two partials and re-applies the reduce graph;
    4. the last scan element of each segment is that group's result.

    The merge is assumed associative, same as the reference ("algebraic
    aggregation", ``Operations.scala:110-120``). Keys may be numeric
    scalars, binary cells, or multi-column mixes (reference
    ``DebugRowOps.scala:547-592``).
    """
    # chunked aggregates recurse through this wrapper on their partial
    # tables, so nested spans (and per-pass row counts) show the recursion
    with _span("engine.aggregate", keys=",".join(grouped_data.keys)):
        out = _aggregate_impl(fetches, grouped_data)
    return out


def _aggregate_impl(fetches, grouped_data: GroupedFrame) -> TensorFrame:
    dframe = grouped_data.frame
    keys = grouped_data.keys
    if not keys:
        raise ValueError("aggregate requires at least one grouping column")
    g = _as_graph(fetches, dframe, cell_inputs=False)
    binding = validate_reduce_block_graph(g, dframe.schema)
    _ensure_precision(g, dframe.schema)
    from . import plan as _plan_mod

    if _plan_mod.enabled():
        # aggregate is a plan terminal: a pending map chain executes as
        # a demand-pruned fused view (bound inputs + group keys only);
        # the lazy frame itself stays lazy — forcing it later yields its
        # full schema (engine/plan.py, docs/pipelines.md)
        dframe = _plan_mod.pruned_view(
            dframe, set(binding.values()) | set(keys)
        )
    import jax
    import jax.numpy as jnp
    from jax import lax

    fetch_names = list(g.fetch_names)
    n = dframe.num_rows
    if n == 0:
        raise ValueError("aggregate on an empty frame")
    _m_rows.inc(n, op="aggregate")

    order, flags, emit_keys = _group_sort(dframe, keys, binding)

    progs = getattr(g, "_agg_scan_cache", None)
    if progs is None:

        def merge_pair(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
            feed = {
                f"{f}_input": jnp.stack([a[f], b[f]]) for f in fetch_names
            }
            return g.fn(feed)

        vmerge = jax.vmap(merge_pair)

        def scan_body(block_feed: Dict[str, Any], flags_: Any) -> Dict[str, Any]:
            # per-row partials: reduce graph applied to blocks of one row
            per_row = jax.vmap(
                lambda cells: g.fn(
                    {
                        f"{f}_input": cells[f][None] for f in fetch_names
                    }
                )
            )({f: block_feed[f] for f in fetch_names})

            def combine(x, y):
                vx, fx = x
                vy, fy = y
                merged = vmerge(vx, vy)
                out = {}
                for f in fetch_names:
                    fy_b = fy.reshape(fy.shape + (1,) * (merged[f].ndim - 1))
                    out[f] = jnp.where(fy_b, vy[f], merged[f])
                return out, fx | fy

            scanned, _ = lax.associative_scan(combine, (per_row, flags_), axis=0)
            return scanned

        # plain jit for small frames; vmap-over-chunks for large ones (the
        # chunked program's scan depth is fixed at log2(_AGG_CHUNK), so
        # compile time stops growing with the frame)
        progs = (jax.jit(scan_body), jax.jit(jax.vmap(scan_body)))
        g._agg_scan_cache = progs
    scan_fn, chunked_fn = progs

    # feed gather happens on device: column -> HBM once (memoized), then a
    # device gather by the sorted order — the host never touches the values
    order_dev = jnp.asarray(order)
    sorted_feed = {
        f: dframe.column_data(col).device()[order_dev]
        for f, col in binding.items()
    }

    if n > _AGG_CHUNK:
        # -- chunked path: pad to a multiple of the chunk, force a segment
        # restart at every chunk boundary, scan all chunks in parallel with
        # one fixed-depth program, then merge the boundary partials by
        # recursing on the (tiny) per-chunk-per-group partial table — the
        # same partial/final shape as the distributed engine's shard merge.
        m = -(-n // _AGG_CHUNK)
        n_pad = m * _AGG_CHUNK
        flags_p = np.zeros(n_pad, dtype=bool)
        flags_p[:n] = flags
        flags_p[np.arange(m) * _AGG_CHUNK] = True
        if n_pad > n:
            flags_p[n] = True  # padding forms its own garbage segment
        starts = np.nonzero(flags_p[:n])[0]
        ends = np.append(starts[1:] - 1, n - 1)
        if len(ends) > n // 2:
            # nearly-unique keys: the partial table cannot shrink enough for
            # the recursion to make progress (equal-size recursion would
            # never terminate), so scan the whole frame in one log2(n)-depth
            # program instead — slower to compile, but correct at any group
            # count
            ends = None
    else:
        ends = None

    if ends is not None:
        feed_r = {}
        for f, arr in sorted_feed.items():
            pad_width = [(0, n_pad - n)] + [(0, 0)] * (arr.ndim - 1)
            padded = jnp.pad(arr, pad_width)
            feed_r[f] = padded.reshape((m, _AGG_CHUNK) + arr.shape[1:])
        scanned = chunked_fn(feed_r, flags_p.reshape(m, _AGG_CHUNK))
        ci = jnp.asarray(ends // _AGG_CHUNK)
        co = jnp.asarray(ends % _AGG_CHUNK)
        partial_cols: Dict[str, Any] = dict(emit_keys(ends))
        for f in fetch_names:
            partial_cols[f] = scanned[f][ci, co]  # device gather, #partials rows
        partials = TensorFrame.from_columns(partial_cols).analyze()
        # cache the renamed final-merge graph ON g: a fresh CapturedGraph
        # per pass would drop its jitted scan programs and recompile the
        # final scan on every aggregate call
        g2 = getattr(g, "_agg_final_graph", None)
        if g2 is None:
            g2 = g._agg_final_graph = g.with_inputs(
                {f"{f}_input": f for f in fetch_names}
            )
        # the partial table's KEY STRUCTURE (sort order, segment flags) is
        # deterministic for a given parent frame + keys + chunking, even
        # though its values change per pass — seed the fresh frame's sort
        # cache with the previous pass's WHOLE cache dict (it also carries
        # the deeper recursion levels' seeds), so repeated aggregates skip
        # every per-level device sync after the first pass
        seed_key = (tuple(keys), "__partials__", len(ends))
        seed = dframe._group_sort_cache.get(seed_key)
        if seed is not None:
            partials._group_sort_cache = seed
        result = aggregate(g2, GroupedFrame(partials, keys))
        dframe._group_sort_cache[seed_key] = getattr(
            partials, "_group_sort_cache", {}
        )
        return result

    out_specs = g.analyze(
        {
            f"{f}_input": dframe.schema[col].block_shape.with_lead(Unknown)
            for f, col in binding.items()
        }
    )
    scanned = scan_fn(sorted_feed, flags)
    # last row of each segment holds that group's reduce
    ends = np.append(np.nonzero(flags[1:])[0], n - 1)
    cols: Dict[str, _ColumnData] = {}
    infos: List[ColumnInfo] = []
    for k, kdata in emit_keys(ends).items():
        cd, _ = _build_column(k, kdata)
        cols[k] = cd
        infos.append(dframe.schema[k])
    for f in fetch_names:
        cols[f] = _ColumnData(dense=scanned[f][jnp.asarray(ends)])
        infos.append(_fetch_column_info(f, out_specs[f], block_output=False))
    return TensorFrame(cols, FrameInfo(infos))


# ---------------------------------------------------------------------------
# analyze / print_schema / explain
# ---------------------------------------------------------------------------


def analyze(dframe: TensorFrame) -> TensorFrame:
    """Deep shape analysis (``core.py:362-375``); see
    :meth:`TensorFrame.analyze`."""
    return dframe.analyze()


def explain(dframe: TensorFrame, analyze: bool = False) -> str:
    """Detailed schema string (reference ``DebugRowOps.explain``,
    ``DebugRowOps.scala:528-545``) — and, for a pending planned frame,
    the logical plan first: recorded nodes, which rewrite passes fire,
    pruned columns, and the fused program count (``engine/plan.py``).
    Pure: rendering the plan neither forces the frame nor executes it.

    ``analyze=True`` appends the per-program cost table from the
    observatory's registry (``obs/programs.py``): every compiled
    program this process has dispatched, with compile wall-time,
    FLOP/byte estimates, invocation counts, cumulative dispatch time,
    and roofline utilization — what a forced pipeline actually cost
    (docs/observability.md) — followed by the autotuner's installed
    tuned configs (``tensorframes_tpu.tune``; docs/tuning.md)."""
    from . import plan as _plan_mod

    schema_txt = dframe.schema.explain()
    plan_txt = _plan_mod.explain_plan(dframe)
    if plan_txt is None:
        out = schema_txt
    else:
        out = f"{plan_txt}\n== Schema ==\n{schema_txt}"
    if analyze:
        out = f"{out}\n{_programs.render_table()}"
        from .. import tune as _tune

        out = f"{out}\n{_tune.render_table()}"
    return out


def print_schema(dframe: TensorFrame) -> None:
    """Print the tensor schema (``core.py:351-360``)."""
    print(explain(dframe))
