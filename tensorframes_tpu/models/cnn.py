"""VGG-style convolutional image models as pure JAX functions.

The reference's flagship binary workload scores a frozen VGG-16 GraphDef
over ``sc.binaryFiles`` rows with ``map_rows`` + a ``feed_dict``-bound
string tensor (``/root/reference/src/main/python/tensorframes_snippets/
read_image.py:147-167``). This module is the first-class equivalent: a
multi-layer conv net whose parameters are a pytree, scored through the
dataframe ops as a captured XLA program ("frozen" = params closed over as
constants, the same role as the reference's ``convert_variables_to_constants``
freezing at ``core.py:41-55``).

TPU notes: convs run NHWC with HWIO filters — the layout XLA:TPU tiles onto
the MXU — and images may arrive as uint8 (the cast to float happens on
device, so the host→HBM transfer carries 1 byte/pixel, not 4).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["init_cnn", "cnn_embed", "cnn_logits", "CNNScorer"]

Params = Dict[str, Any]


def init_cnn(
    seed: int,
    input_hw: Tuple[int, int] = (32, 32),
    channels: int = 3,
    block_widths: Sequence[int] = (32, 64, 128),
    convs_per_block: int = 2,
    embed_dim: int = 256,
    num_classes: Optional[int] = None,
    dtype=np.float32,
) -> Params:
    """He-initialized VGG-style net: ``len(block_widths)`` blocks of
    ``convs_per_block`` 3x3 convs + 2x2 maxpool, then a dense embedding
    head (and an optional classifier head)."""
    rng = np.random.default_rng(seed)
    h, w = input_hw
    convs: List[Dict[str, np.ndarray]] = []
    c_in = channels
    for width in block_widths:
        for _ in range(convs_per_block):
            fan_in = 3 * 3 * c_in
            k = rng.normal(0.0, np.sqrt(2.0 / fan_in), (3, 3, c_in, width))
            convs.append(
                {"k": k.astype(dtype), "b": np.zeros((width,), dtype=dtype)}
            )
            c_in = width
        h, w = h // 2, w // 2
    if h < 1 or w < 1:
        raise ValueError(
            f"input {input_hw} too small for {len(block_widths)} pool stages"
        )
    flat = h * w * c_in
    params: Params = {
        "convs": convs,
        "convs_per_block": convs_per_block,
        "embed": {
            "w": rng.normal(0.0, np.sqrt(2.0 / flat), (flat, embed_dim)).astype(dtype),
            "b": np.zeros((embed_dim,), dtype=dtype),
        },
    }
    if num_classes is not None:
        params["head"] = {
            "w": rng.normal(
                0.0, np.sqrt(2.0 / embed_dim), (embed_dim, num_classes)
            ).astype(dtype),
            "b": np.zeros((num_classes,), dtype=dtype),
        }
    return params


def _maxpool2(x):
    import jax.lax as lax

    return lax.reduce_window(
        x, -np.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def cnn_embed(params: Params, images, compute_dtype=None):
    """Embeddings for a batch of NHWC images. uint8 input is normalized to
    [0, 1] on device; ``compute_dtype`` (e.g. ``jnp.bfloat16``) selects the
    MXU precision, with the embedding returned in f32."""
    import jax
    import jax.numpy as jnp
    import jax.lax as lax

    x = images
    if x.dtype == jnp.uint8:
        x = x.astype(jnp.float32) / 255.0
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
    per_block = params["convs_per_block"]
    for i, layer in enumerate(params["convs"]):
        k = layer["k"].astype(x.dtype) if compute_dtype is not None else layer["k"]
        x = lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        x = jax.nn.relu(x + layer["b"].astype(x.dtype))
        if (i + 1) % per_block == 0:
            x = _maxpool2(x)
    x = x.reshape(x.shape[0], -1)
    emb = x @ params["embed"]["w"].astype(x.dtype) + params["embed"]["b"].astype(x.dtype)
    return emb.astype(jnp.float32)


def cnn_logits(params: Params, images, compute_dtype=None):
    if "head" not in params:
        raise ValueError("init_cnn(num_classes=...) required for logits")
    emb = cnn_embed(params, images, compute_dtype=compute_dtype)
    return emb @ params["head"]["w"] + params["head"]["b"]


class CNNScorer:
    """Frozen-CNN scoring over frames — the reference's VGG-over-binary-rows
    workload (``read_image.py:147-167``) as a model object.

    ``score_frame`` takes a frame with a binary column of raw image bytes,
    decodes on the host (:meth:`TensorFrame.decode_column` thread pool), and
    scores batched on device — one XLA program per partition block instead
    of one Session.run per row.
    """

    def __init__(
        self, params: Params, input_hw=(32, 32), channels=3, codec=None
    ):
        self.params = params
        self.input_hw = tuple(input_hw)
        self.channels = channels
        #: bytes -> uint8 HWC array; defaults to the raw-packed-bytes
        #: stand-in. Pass ``tensorframes_tpu.data.image_decoder(...)`` for
        #: real PNG/JPEG rows (the reference's decode_jpeg stage).
        self._codec = codec
        # graph capture and compiled programs are memoized by FUNCTION
        # IDENTITY; a fresh embed closure per score_frame call would
        # re-capture (and re-run the concrete probe) every pass
        self._embed_fns: Dict[Tuple[str, str], Any] = {}

    @staticmethod
    def init(seed: int, input_hw=(32, 32), channels=3, **kw) -> "CNNScorer":
        return CNNScorer(
            init_cnn(seed, input_hw=input_hw, channels=channels, **kw),
            input_hw=input_hw,
            channels=channels,
        )

    @staticmethod
    def from_pretrained(
        path: str,
        input_hw: Tuple[int, int],
        channels: int = 3,
        convs_per_block: Optional[int] = None,
        layout: str = "torch",
        image_format: str = "encoded",
    ) -> "CNNScorer":
        """Load externally-published weights into a frozen scorer — the
        reference's download-VGG-then-freeze flow (``read_image.py:29-55``
        + ``core.py:41-55``) as one constructor.

        ``layout="torch"`` converts a torch ``state_dict`` (NCHW/OIHW,
        ``[out,in]`` linears, C*H*W flatten) via
        :func:`~tensorframes_tpu.interop.cnn_params_from_torch_state`;
        ``layout="native"`` loads a :func:`flatten_tree`-saved params
        pytree verbatim — its SAVED ``convs_per_block`` is the
        architecture of record and wins over the argument (which only
        fills in for checkpoints that lack it). ``image_format="encoded"``
        wires a real PNG/JPEG codec (with bilinear resize to
        ``input_hw``); ``"raw"`` keeps the packed-bytes stand-in."""
        from ..interop.weights import (
            cnn_params_from_torch_state,
            load_weights,
            unflatten_tree,
        )

        flat = load_weights(path)
        if layout == "torch":
            params = cnn_params_from_torch_state(
                flat, input_hw, channels,
                convs_per_block=(
                    2 if convs_per_block is None else convs_per_block
                ),
            )
        elif layout == "native":
            params = unflatten_tree(flat)
            if "convs_per_block" in params:
                # saved as a 0-d array by npz/safetensors; the model code
                # needs the plain int back
                params["convs_per_block"] = int(
                    np.asarray(params["convs_per_block"])
                )
            elif convs_per_block is not None:
                params["convs_per_block"] = convs_per_block
            else:
                raise ValueError(
                    "native checkpoint lacks convs_per_block; pass it "
                    "explicitly"
                )
        else:
            raise ValueError(f"layout must be 'torch' or 'native', got {layout!r}")
        codec = None
        if image_format == "encoded":
            from ..data.codecs import image_decoder

            codec = image_decoder(resize_hw=input_hw, channels=channels)
        elif image_format != "raw":
            raise ValueError(
                f"image_format must be 'encoded' or 'raw', got {image_format!r}"
            )
        return CNNScorer(
            params, input_hw=input_hw, channels=channels, codec=codec
        )

    def decode(self, raw: bytes) -> np.ndarray:
        """Binary cell -> uint8 HWC image, via the configured codec (real
        PNG/JPEG decode for ``from_pretrained(image_format="encoded")``
        scorers, raw packed bytes otherwise)."""
        if self._codec is not None:
            return self._codec(raw)
        h, w = self.input_hw
        return np.frombuffer(raw, dtype=np.uint8).reshape(h, w, self.channels)

    def score_frame(
        self,
        df,
        col: str,
        embedding_col: str = "embedding",
        engine=None,
        compute_dtype="bfloat16",
    ):
        """Decode ``col`` (binary) and append ``embedding_col``. ``engine``
        defaults to the local engine; pass ``tensorframes_tpu.parallel`` to
        shard the scoring over the mesh.

        ``map_blocks`` programs see a whole partition block, so the block
        size is the activation-memory knob; the result is repartitioned
        upward when needed so no block exceeds
        ``config.max_rows_per_device_call`` rows (block *count* may
        therefore differ from the input frame's). Chunking inside a block
        is not an option in general — block programs may compute
        cross-row statistics — so the split happens at the partition
        level, which is semantically free."""
        from .. import engine as local_engine

        eng = engine or local_engine
        params = self.params

        fn_key = (embedding_col, compute_dtype)
        embed_fn = self._embed_fns.get(fn_key)
        if embed_fn is None:

            def embed_fn(images):
                import jax.numpy as jnp

                dt = jnp.bfloat16 if compute_dtype == "bfloat16" else None
                return {
                    embedding_col: cnn_embed(params, images, compute_dtype=dt)
                }

            self._embed_fns[fn_key] = embed_fn

        from ..utils import get_config

        cap = max(1, get_config().max_rows_per_device_call)
        binary = df.schema[col].scalar_type.name == "binary"
        if binary and eng is local_engine:
            # overlapped path: the codec runs on a thread pool several
            # partition blocks AHEAD of the chip (map_blocks decoders=),
            # so host decode hides under device compute instead of
            # serializing before it
            need = -(-df.num_rows // cap)
            if df.num_partitions < need:
                df = df.repartition(need)
            return eng.map_blocks(
                embed_fn,
                df,
                feed_dict={"images": col},
                decoders={"images": self.decode},
            )
        if binary:
            decoded = df.decode_column(col, self.decode).analyze()
        else:
            decoded = df.analyze()  # already decoded (e.g. cached upstream)
        # map_blocks runs one XLA program per partition block, so conv
        # activation memory scales with the block; split so no block
        # exceeds the map_rows per-call row cap
        need = -(-decoded.num_rows // cap)
        if decoded.num_partitions < need:
            decoded = decoded.repartition(need)
        return eng.map_blocks(embed_fn, decoded, feed_dict={"images": col})
