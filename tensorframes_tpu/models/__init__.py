"""Model zoo: the reference's model-scoring workloads as first-class models."""

from .mlp import (
    MLPClassifier,
    init_mlp,
    mlp_apply,
    mlp_logits,
    mlp_loss,
    softmax_cross_entropy,
)
from .cnn import CNNScorer, cnn_embed, cnn_logits, init_cnn
from .kmeans import kmeans, assign_clusters
from .transformer import (
    TransformerLM,
    filter_logits,
    init_draft_transformer,
    init_transformer,
    left_pad_prompts,
    transformer_generate,
    transformer_logits,
    transformer_loss,
    transformer_prefill,
    transformer_step,
)

__all__ = [
    "CNNScorer",
    "cnn_embed",
    "cnn_logits",
    "init_cnn",
    "TransformerLM",
    "init_draft_transformer",
    "init_transformer",
    "transformer_generate",
    "transformer_logits",
    "transformer_loss",
    "transformer_prefill",
    "transformer_step",
    "filter_logits",
    "left_pad_prompts",
    "MLPClassifier",
    "init_mlp",
    "mlp_apply",
    "mlp_logits",
    "mlp_loss",
    "softmax_cross_entropy",
    "kmeans",
    "assign_clusters",
]
