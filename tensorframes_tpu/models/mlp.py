"""MLP / logistic-regression models as pure JAX functions.

The reference has no model code of its own — its model workloads are frozen
TF graphs scored through the dataframe ops: MNIST logistic regression via
``map_blocks`` (variable-freezing path, reference ``core.py:41-55``) and
VGG/Inception image scoring via ``map_rows``
(``tensorframes_snippets/read_image.py:147-167``). This module provides the
equivalent first-class models: parameters are pytrees, scoring is a captured
graph dispatched through ``map_blocks``, and training composes with
:mod:`tensorframes_tpu.parallel.training` for mesh-sharded SGD.

A zero-hidden-layer MLP is exactly the reference's logistic-regression
scoring workload (BASELINE.md config 3).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "init_mlp",
    "mlp_apply",
    "mlp_logits",
    "softmax_cross_entropy",
    "mlp_loss",
    "MLPClassifier",
]

Params = List[Dict[str, Any]]


def init_mlp(
    seed: int, layer_sizes: Sequence[int], dtype=np.float32
) -> Params:
    """He-initialized dense layers: ``layer_sizes = [din, h1, ..., dout]``."""
    if len(layer_sizes) < 2:
        raise ValueError("layer_sizes needs at least [din, dout]")
    rng = np.random.default_rng(seed)
    params: Params = []
    for fan_in, fan_out in zip(layer_sizes[:-1], layer_sizes[1:]):
        w = rng.normal(0.0, np.sqrt(2.0 / fan_in), (fan_in, fan_out))
        params.append(
            {
                "w": w.astype(dtype),
                "b": np.zeros((fan_out,), dtype=dtype),
            }
        )
    return params


def mlp_logits(params: Params, x):
    """Forward pass to logits. Matmuls stay batched 2-D so XLA tiles them
    onto the MXU; bf16/f32 inputs pass through unchanged."""
    import jax

    h = x
    for i, layer in enumerate(params):
        h = h @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            h = jax.nn.relu(h)
    return h


def mlp_apply(params: Params, x):
    """Class probabilities."""
    import jax

    return jax.nn.softmax(mlp_logits(params, x), axis=-1)


def softmax_cross_entropy(logits, labels):
    """Mean CE over the batch; ``labels`` are int class ids."""
    import jax
    import jax.numpy as jnp

    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)
    return -jnp.mean(picked)


def mlp_loss(params: Params, x, y):
    return softmax_cross_entropy(mlp_logits(params, x), y)


class MLPClassifier:
    """Convenience wrapper: holds params, scores frames through the engine.

    ``score_frame`` is the analog of scoring a frozen graph with
    ``tfs.map_blocks`` (reference ``core.py:41-55`` + BASELINE config 3):
    the parameters are closed over as constants in the captured program,
    exactly like the reference freezes ``tf.Variable`` into the GraphDef.
    """

    def __init__(self, params: Params):
        self._params = params
        self._graph_cache: Dict[Any, Any] = {}

    @property
    def params(self) -> Params:
        return self._params

    @params.setter
    def params(self, new_params: Params) -> None:
        # captured scoring graphs close over the old weights; drop them
        self._params = new_params
        self._graph_cache.clear()

    @staticmethod
    def init(seed: int, layer_sizes: Sequence[int], dtype=np.float32):
        return MLPClassifier(init_mlp(seed, layer_sizes, dtype))

    def _scoring_graph(
        self, df, col, prediction_col, probabilities_col
    ):
        """CapturedGraph for scoring, memoized so repeated scoring reuses one
        compiled program (the reference broadcasts one frozen GraphDef and
        reuses it per partition; rebuilding the capture per call would force
        an XLA recompile per call)."""
        from ..capture import CapturedGraph
        from ..schema import Unknown

        info = df.schema[col]
        key = (
            col,
            prediction_col,
            probabilities_col,
            info.scalar_type.name,
            info.cell_shape.dims,
        )
        if key in self._graph_cache:
            return self._graph_cache[key]
        import jax
        import jax.numpy as jnp

        params = self.params

        def fn(x):
            logits = mlp_logits(params, x)
            out = {prediction_col: jnp.argmax(logits, axis=-1).astype(jnp.int32)}
            if probabilities_col:
                out[probabilities_col] = jax.nn.softmax(logits, axis=-1)
            return out

        g = CapturedGraph.from_callable(
            fn,
            {"x": (info.scalar_type, info.block_shape.with_lead(Unknown))},
            inputs_map={"x": col},
        )
        self._graph_cache[key] = g
        return g

    def score_frame(
        self,
        df,
        col: str,
        prediction_col: str = "prediction",
        probabilities_col: Optional[str] = None,
        distributed: bool = False,
        mesh=None,
    ):
        """Append argmax predictions (and optionally probabilities) to the
        frame via ``map_blocks``."""
        g = self._scoring_graph(df, col, prediction_col, probabilities_col)
        if distributed:
            from ..parallel import map_blocks as dmap_blocks

            return dmap_blocks(g, df, mesh=mesh)
        from ..engine import map_blocks

        return map_blocks(g, df)
