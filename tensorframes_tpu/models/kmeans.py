"""Distributed k-means built on the dataframe ops.

Port of the reference's flagship snippet pair
(``/root/reference/src/main/python/tensorframes_snippets/kmeans.py:105-148``
and the optimized ``kmeans_demo.py:101-171``): each iteration pre-aggregates
*inside the captured program* — per-block per-cluster sums and counts via
segment-sum — emitting one row per block (``map_blocks(trim=True)``), then a
global ``reduce_blocks`` sums the per-block partials. Communication per
iteration is O(num_blocks * k * d), independent of the row count, exactly
the trick the reference demo uses to beat its own Spark-aggregation variant.

TPU-first details the reference couldn't have: centroids are a per-call
``constants`` input (an ordinary traced argument), so all Lloyd iterations
share ONE compiled XLA program — where the reference rebuilds and re-ships
a GraphDef with fresh constant centroids every iteration. The distance
matrix and segment sums run on the MXU/VPU; with ``distributed=True`` the
per-block phase is one ``shard_map`` program across the mesh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["kmeans", "assign_clusters"]

#: per-column pre-aggregation functions (see kmeans(): capture is memoized
#: by function identity, so the function object must be stable across calls)
_pre_fn_cache: dict = {}


def _pre_agg(features, centroids):
    """Per-block partials: [k, d] cluster sums and [k] counts, emitted as a
    single row (cell tensors of order 2/1, within the engine's limits)."""
    import jax
    import jax.numpy as jnp

    k = centroids.shape[0]
    d2 = ((features[:, None, :] - centroids[None, :, :]) ** 2).sum(axis=-1)
    closest = jnp.argmin(d2, axis=1)
    sums = jax.ops.segment_sum(features, closest, num_segments=k)
    counts = jax.ops.segment_sum(
        jnp.ones_like(closest, dtype=features.dtype), closest, num_segments=k
    )
    return {"sums": sums[None], "counts": counts[None]}


def _merge_partials(sums_input, counts_input):
    return {
        "sums": sums_input.sum(axis=0),
        "counts": counts_input.sum(axis=0),
    }


def _with_signature(fn, params):
    import inspect

    fn.__signature__ = inspect.Signature(
        [
            inspect.Parameter(p, inspect.Parameter.POSITIONAL_OR_KEYWORD)
            for p in params
        ]
    )
    return fn


def kmeans(
    df,
    col: str,
    k: int,
    num_iters: int = 10,
    seed: int = 0,
    distributed: bool = False,
    mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Lloyd iterations over a frame column of feature vectors.

    Returns ``(centroids [k, d], centroid_shift_history)``. Mirrors the
    reference demo's ``run_tf_kmeans`` (``kmeans_demo.py:198-230``)."""
    data0 = np.asarray(df.column_block(col))
    n, _ = data0.shape
    rng = np.random.default_rng(seed)
    centroids = data0[rng.choice(n, size=k, replace=False)].astype(data0.dtype)

    # one function object per COLUMN NAME, cached at module scope: graph
    # capture is memoized by function identity, so a fresh lambda per
    # kmeans() call would re-capture (and re-trace) on every invocation —
    # with the cache, repeated kmeans() calls (warmup, CV folds, demos)
    # reuse one captured graph and one compiled program
    pre_fn = _pre_fn_cache.get(col)
    if pre_fn is None:
        pre_fn = _pre_fn_cache[col] = _with_signature(
            lambda **cols: _pre_agg(cols[col], cols["centroids"]),
            [col, "centroids"],
        )

    if distributed:
        from ..parallel import map_blocks, reduce_blocks

        def run_map(consts):
            return map_blocks(pre_fn, df, mesh=mesh, trim=True, constants=consts)

        def run_reduce(partials):
            return reduce_blocks(_merge_partials, partials, mesh=mesh)

    else:
        from ..engine import map_blocks, reduce_blocks

        def run_map(consts):
            return map_blocks(pre_fn, df, trim=True, constants=consts)

        def run_reduce(partials):
            return reduce_blocks(_merge_partials, partials)

    history = []
    for _ in range(num_iters):
        partials = run_map({"centroids": centroids}).cache().analyze()
        counts, sums = run_reduce(partials)  # sorted fetch order
        sums = np.asarray(sums)
        counts = np.asarray(counts)
        nonempty = counts > 0
        new_centroids = centroids.copy()
        new_centroids[nonempty] = (
            sums[nonempty] / counts[nonempty, None]
        ).astype(centroids.dtype)
        shift = float(np.linalg.norm(new_centroids - centroids))
        history.append(shift)
        centroids = new_centroids
        if shift == 0.0:
            break
    return centroids, np.asarray(history)


def _assign_fn_factory(col, index_col, distance_col):
    def fn(**cols):
        import jax.numpy as jnp

        x = cols[col]
        c = cols["centroids"]
        d2 = ((x[:, None, :] - c[None, :, :]) ** 2).sum(axis=-1)
        out = {index_col: jnp.argmin(d2, axis=1).astype(jnp.int32)}
        if distance_col:
            out[distance_col] = jnp.sqrt(d2.min(axis=1))
        return out

    return _with_signature(fn, [col, "centroids"])


def assign_clusters(
    df,
    col: str,
    centroids: np.ndarray,
    index_col: str = "closest_centroid",
    distance_col: Optional[str] = "distance",
):
    """Append nearest-centroid index (and distance) columns — the reference's
    basic k-means assignment map (``kmeans.py:105-132``)."""
    from ..engine import map_blocks

    fn = _assign_fn_factory(col, index_col, distance_col)
    return map_blocks(fn, df, constants={"centroids": np.asarray(centroids)})
