"""Decoder-only transformer LM on the framework's attention stack.

The reference's deepest model workload is scoring a frozen VGG/Inception
graph through the dataframe ops (``read_image.py:147-167``); this module is
the modern analog: a transformer whose attention runs on the Pallas flash
kernel single-chip (:func:`tensorframes_tpu.ops.flash_attention`) or on
ring attention across the ``sp`` mesh axis for long sequences
(:func:`tensorframes_tpu.ops.ring_attention`), and whose scoring dispatches
through ``map_blocks`` like any other captured program.

Architecture: learned positional embeddings, pre-LN blocks
(MHA -> residual, GELU MLP -> residual), final LN, tied output head.
All matmuls stay [tokens, d] x [d, d'] so XLA tiles them onto the MXU.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "init_transformer",
    "init_draft_transformer",
    "transformer_logits",
    "transformer_generate",
    "transformer_step",
    "transformer_prefill",
    "transformer_prefill_chunk",
    "transformer_verify_chunk",
    "transformer_tp_specs",
    "gather_tp_params",
    "transformer_loss",
    "token_nll",
    "TransformerLM",
    "filter_logits",
    "left_pad_prompts",
]

Params = Dict[str, Any]


def init_transformer(
    seed: int,
    vocab: int,
    d_model: int = 64,
    n_heads: int = 4,
    n_layers: int = 2,
    max_len: int = 128,
    d_ff: Optional[int] = None,
    moe_experts: Optional[int] = None,
    n_kv_heads: Optional[int] = None,
    dtype=np.float32,
) -> Params:
    """``moe_experts``: replace every block's dense MLP with a top-1
    routed mixture of that many experts (:mod:`..parallel.moe`); the
    expert slabs shard over an ``ep`` mesh axis at apply time.

    ``n_kv_heads``: grouped-query attention (GQA) — k/v get this many
    heads (must divide ``n_heads``; default = ``n_heads`` = standard
    MHA, ``1`` = MQA), each shared by ``n_heads / n_kv_heads`` query
    heads. The fused qkv projection shrinks to
    ``[d, d + 2 * n_kv_heads * head_dim]`` and the decode KV cache
    holds only ``n_kv_heads`` heads — the cache (usually the decode
    memory ceiling) shrinks by the group factor."""
    if d_model % n_heads:
        raise ValueError(f"d_model {d_model} must divide by n_heads {n_heads}")
    n_kv_heads = n_heads if n_kv_heads is None else n_kv_heads
    if n_kv_heads < 1 or n_heads % n_kv_heads:
        raise ValueError(
            f"n_heads {n_heads} must divide by n_kv_heads {n_kv_heads} "
            f"(>= 1)"
        )
    d_ff = d_ff or 4 * d_model
    kv_d = (d_model // n_heads) * n_kv_heads
    rng = np.random.default_rng(seed)

    def dense(fan_in, fan_out):
        return (rng.normal(0, fan_in**-0.5, (fan_in, fan_out))).astype(dtype)

    params: Params = {
        "embed": (rng.normal(0, 0.02, (vocab, d_model))).astype(dtype),
        "pos": (rng.normal(0, 0.02, (max_len, d_model))).astype(dtype),
        "blocks": [],
        "ln_f": {"g": np.ones(d_model, dtype), "b": np.zeros(d_model, dtype)},
        # n_kv_heads is NOT stored: it is derivable from the qkv weight's
        # static column count (see _kv_heads), so every site that strips
        # the one non-array entry ("n_heads") before device_put stays
        # unchanged and old checkpoints load as plain MHA
        "n_heads": n_heads,
    }
    for li in range(n_layers):
        block = {
            "ln1": {"g": np.ones(d_model, dtype), "b": np.zeros(d_model, dtype)},
            "qkv": dense(d_model, d_model + 2 * kv_d),
            "proj": dense(d_model, d_model),
            "ln2": {"g": np.ones(d_model, dtype), "b": np.zeros(d_model, dtype)},
        }
        if moe_experts is None:
            block["up"] = dense(d_model, d_ff)
            block["down"] = dense(d_ff, d_model)
        else:
            from ..parallel.moe import init_moe

            # derive expert seeds from the model rng so they never collide
            # with the main seed (seed*k+li would reuse generator streams)
            block["moe"] = init_moe(
                int(rng.integers(0, 2**31)), d_model, d_ff, moe_experts,
                dtype=dtype,
            )
        params["blocks"].append(block)
    return params


def init_draft_transformer(
    target_params: Params,
    seed: int,
    *,
    d_model: Optional[int] = None,
    n_heads: Optional[int] = None,
    n_layers: Optional[int] = None,
    d_ff: Optional[int] = None,
    n_kv_heads: Optional[int] = None,
    dtype=None,
) -> Params:
    """A small DRAFT model for speculative decoding, derived from a
    target model's params: same vocabulary and positional table (the
    two properties the serving engine's draft/verify contract requires
    — draft proposals are token ids in the target's vocab, and the
    draft must reach every position the target can), smaller everything
    else. Defaults: half the target's layers, the target's width/heads.
    The draft is a plain :func:`init_transformer` model — train or
    distill it like any other; the serving engine only needs the params
    (``GenerationEngine(..., draft_params=...)``,
    docs/serving_llm.md "Speculative decoding")."""
    vocab = int(np.shape(target_params["embed"])[0])
    tgt_d = int(np.shape(target_params["embed"])[1])
    max_len = int(np.shape(target_params["pos"])[0])
    tgt_heads = int(target_params["n_heads"])
    d_model = tgt_d if d_model is None else int(d_model)
    n_heads = tgt_heads if n_heads is None else int(n_heads)
    n_layers = (
        max(1, len(target_params["blocks"]) // 2)
        if n_layers is None
        else int(n_layers)
    )
    if dtype is None:
        dtype = np.dtype(
            getattr(target_params["embed"], "dtype", np.float32)
        )
    return init_transformer(
        seed,
        vocab,
        d_model=d_model,
        n_heads=n_heads,
        n_layers=n_layers,
        max_len=max_len,
        d_ff=d_ff,
        n_kv_heads=n_kv_heads,
        dtype=dtype,
    )


def _ln(x, p):
    import jax.numpy as jnp

    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-5) * p["g"] + p["b"]


def _kv_heads(block, d_model: int, n_heads: int) -> int:
    """GQA group count from the qkv weight's STATIC shape: columns are
    ``d + 2 * n_kv * head_dim``, so ``n_kv`` needs no extra stored
    metadata (plain MHA weights give ``n_kv == n_heads``)."""
    kv_d = (int(np.shape(block["qkv"])[1]) - d_model) // 2
    return kv_d // (d_model // n_heads)


def _attention(x, block, n_heads, causal, attn_impl, mesh, batch_axis=None):
    import jax.numpy as jnp

    from ..ops import (
        attention_reference,
        flash_attention,
        ring_attention,
        ulysses_attention,
    )

    bsz, length, d = x.shape
    hd = d // n_heads
    n_kv = _kv_heads(block, d, n_heads)
    kv_d = n_kv * hd
    qkv = x @ block["qkv"]  # [B, L, D + 2*kv_d]
    q, k, v = jnp.split(qkv, [d, d + kv_d], axis=-1)

    def heads(t, h):  # [B, L, h*hd] -> [B, h, L, hd]
        return t.reshape(bsz, length, h, hd).transpose(0, 2, 1, 3)

    q = heads(q, n_heads)
    k = heads(k, n_kv)
    v = heads(v, n_kv)
    if n_kv != n_heads:
        # grouped-query: each k/v head serves n_heads/n_kv query heads.
        # The repeat materializes full-H k/v for the compute path (the
        # kernels are head-uniform); the GQA saving is in the weights and
        # the decode KV cache, which store only n_kv heads.
        k = jnp.repeat(k, n_heads // n_kv, axis=1)
        v = jnp.repeat(v, n_heads // n_kv, axis=1)
    if attn_impl == "ring":
        o = ring_attention(
            q, k, v, mesh=mesh, causal=causal, batch_axis=batch_axis
        )
    elif attn_impl == "ulysses":
        o = ulysses_attention(
            q, k, v, mesh=mesh, causal=causal, batch_axis=batch_axis
        )
    elif attn_impl == "flash":
        o = flash_attention(q, k, v, causal=causal)
    else:
        o = attention_reference(q, k, v, causal=causal)
    o = o.transpose(0, 2, 1, 3).reshape(bsz, length, d)
    return o @ block["proj"]


def _dense_block(
    block, h, n_heads, causal=True, attn_impl="reference", mesh=None,
    batch_axis=None,
):
    """One dense transformer block (pre-LN attention + gelu MLP residuals)
    — THE block forward, shared by the full-model path
    (:func:`transformer_logits`) and the pipelined stage
    (:func:`_pipe_stage_fn`) so the two cannot drift apart."""
    import jax

    x = h + _attention(
        _ln(h, block["ln1"]), block, n_heads, causal, attn_impl, mesh,
        batch_axis,
    )
    return x + (
        jax.nn.gelu(_ln(x, block["ln2"]) @ block["up"]) @ block["down"]
    )


def _head_nll(embed, ln_f, x, targets):
    """Loss head: final norm + tied unembedding + next-token cross entropy
    (mean). Shared by the pipelined loss (:func:`_pipe_loss_fn`); the
    full-model path computes the same math spread across
    :func:`transformer_logits`/:func:`token_nll` (kept separate there
    because scoring needs the per-position NLL, not the mean)."""
    import jax
    import jax.numpy as jnp

    logits = _ln(x, ln_f) @ embed.T
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(
        logp, targets[..., None].astype(jnp.int32), axis=-1
    )
    return -picked[..., 0].mean()


def transformer_logits(
    params: Params,
    tokens,
    causal: bool = True,
    attn_impl: str = "reference",
    mesh=None,
    batch_axis=None,
    collect_moe_aux: bool = False,
    moe_top_k: int = 1,
    moe_impl: str = "masked",
    remat: bool = False,
):
    """``tokens`` [B, L] int32 -> logits [B, L, vocab].

    ``attn_impl``: "reference" (dense, XLA-fused — best for short L),
    "flash" (Pallas kernel), "ring" (K/V rotation over ``mesh``'s sp
    axis), or "ulysses" (all-to-all head-sharding over the same axis;
    needs heads divisible by the axis size).

    MoE blocks route top-``moe_top_k``; ``moe_impl`` picks the expert
    data path on an ``ep`` mesh: "masked" (exact masked compute, every
    chip sees all tokens) or "dispatch" (Switch all-to-all with capacity
    buffers — lower FLOPs/communication at scale, drops overflow
    tokens).

    ``remat=True`` wraps each block in ``jax.checkpoint``: the backward
    pass recomputes block activations instead of saving them, so
    activation memory is O(1) in depth — the standard FLOPs-for-HBM
    trade for long-context / deep training (MoE blocks stay un-rematted
    when ``collect_moe_aux`` needs their intermediate activations)."""
    if moe_impl not in ("masked", "dispatch"):
        raise ValueError(
            f"unknown moe_impl {moe_impl!r}; expected 'masked' or "
            f"'dispatch'"
        )
    if attn_impl not in ("reference", "flash", "ring", "ulysses"):
        raise ValueError(
            f"unknown attn_impl {attn_impl!r}; expected 'reference', "
            f"'flash', 'ring', or 'ulysses'"
        )
    import jax
    import jax.numpy as jnp

    n_heads = params["n_heads"]
    length = tokens.shape[1]
    # params may be host numpy (frozen-model scoring closes over them);
    # jnp-ify before indexing with traced token ids
    embed = jnp.asarray(params["embed"])
    pos = jnp.asarray(params["pos"])
    x = embed[tokens] + pos[:length][None]
    from ..parallel.moe import (
        EXPERT_AXIS,
        moe_apply,
        moe_dispatch_apply,
        moe_ffn,
        moe_load_balance_loss,
    )

    def run_dense(block, h):
        return _dense_block(
            block, h, n_heads, causal, attn_impl, mesh, batch_axis
        )

    def run_moe(block, h_in):
        h = _ln(h_in, block["ln1"])
        y = h_in + _attention(
            h, block, n_heads, causal, attn_impl, mesh, batch_axis
        )
        h = _ln(y, block["ln2"])
        if mesh is not None and EXPERT_AXIS in mesh.axis_names:
            apply = (
                moe_dispatch_apply if moe_impl == "dispatch" else moe_apply
            )
            return y + apply(block["moe"], h, mesh=mesh, k=moe_top_k), h
        return y + moe_ffn(block["moe"], h, k=moe_top_k), h

    if remat:
        run_dense = jax.checkpoint(run_dense)
        if not collect_moe_aux:
            run_moe = jax.checkpoint(run_moe)

    moe_aux = 0.0
    for block in params["blocks"]:
        if "moe" in block:
            x, h_mid = run_moe(block, x)
            if collect_moe_aux:
                moe_aux = moe_aux + moe_load_balance_loss(
                    block["moe"], h_mid, k=moe_top_k
                )
        else:
            x = run_dense(block, x)
    x = _ln(x, params["ln_f"])
    logits = x @ embed.T
    if collect_moe_aux:
        return logits, moe_aux
    return logits


def _is_concrete_scalar(x) -> bool:
    """True when ``x`` is a plain Python/numpy number (its VALUE may steer
    trace-time structure); False for tracers (value unknown — the caller
    must have opted into the sampled/filtered program shape)."""
    return isinstance(x, (int, float, np.integer, np.floating))


def filter_logits(logits, top_k: int = 0, top_p=1.0):
    """Top-k / nucleus (top-p) logit filtering, [B, V] -> [B, V] with
    masked-out entries at a large negative. ``top_k`` is static (0 = off);
    ``top_p`` may be a traced scalar (1.0 = off when concrete). Nucleus
    keeps the smallest prefix of descending-probability tokens whose
    cumulative mass reaches ``top_p`` (the first token always survives, so
    a tiny top_p degrades to greedy, not to an empty support)."""
    import jax
    import jax.numpy as jnp

    neg = jnp.finfo(jnp.float32).min * 0.7
    if top_k and top_k > 0:
        # top_k >= vocab keeps everything (lax.top_k would fail the trace
        # with an opaque XLA shape error instead)
        k = min(int(top_k), logits.shape[-1])
        if k < logits.shape[-1]:
            kth = jax.lax.top_k(logits, k)[0][..., -1:]
            logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and not (
        _is_concrete_scalar(top_p) and top_p >= 1.0
    ):
        sl = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        ps = jax.nn.softmax(sl, axis=-1)
        css = jnp.cumsum(ps, axis=-1)
        # token j (sorted order) kept iff the mass BEFORE it is < top_p
        keep = (css - ps) < top_p
        k_eff = keep.sum(axis=-1, keepdims=True)  # >= 1 by construction
        thresh = jnp.take_along_axis(sl, k_eff - 1, axis=-1)
        logits = jnp.where(logits < thresh, neg, logits)
    return logits


def left_pad_prompts(seqs, pad_id: int = 0):
    """Pack variable-length prompts into the left-padded ``[B, P]`` layout
    :func:`transformer_generate` takes for ragged batches (each row's
    tokens right-aligned at positions ``P-len..P-1``). Returns
    ``(prompt, lengths)``."""
    lengths = np.asarray([len(s) for s in seqs], dtype=np.int32)
    if (lengths < 1).any():
        raise ValueError("every prompt needs at least one token")
    p = int(lengths.max())
    out = np.full((len(seqs), p), pad_id, dtype=np.int32)
    for i, s in enumerate(seqs):
        out[i, p - len(s):] = np.asarray(s, dtype=np.int32)
    return out, lengths


def transformer_step(params, tok, positions, attend, moe_top_k: int = 1):
    """One decoder step for a batch of single tokens — THE per-token block
    walk, shared by the scan decode (:func:`transformer_generate`) and the
    paged serving engine (:mod:`tensorframes_tpu.serve`) so the two decode
    paths cannot drift apart.

    ``tok`` [B] int32 current tokens; ``positions`` [B] int32 positional
    indices (already offset/clipped by the caller). Attention is delegated
    to ``attend(li, q, k, v) -> [B, d_model]``: the callback owns the KV
    state — it receives layer ``li``'s query ``[B, n_kv, group, hd]``
    (grouped-query layout; ``group == 1`` rows share a k/v head) and this
    step's k/v ``[B, n_kv, hd]``, stores k/v wherever the caller keeps its
    cache (scan-carried dense cache, paged pool), reads the visible
    history, and returns the pre-``proj`` attention context. Returns
    logits ``[B, vocab]``."""
    import jax
    import jax.numpy as jnp

    from ..parallel.moe import moe_ffn

    embed = jnp.asarray(params["embed"])
    posemb = jnp.asarray(params["pos"])
    n_heads = params["n_heads"]
    d_model = embed.shape[1]
    hd = d_model // n_heads
    bsz = tok.shape[0]
    h = embed[tok] + posemb[positions]
    for li, block in enumerate(params["blocks"]):
        n_kv = _kv_heads(block, d_model, n_heads)
        group = n_heads // n_kv
        kv_d = n_kv * hd
        x = _ln(h, block["ln1"])
        qkv = x @ jnp.asarray(block["qkv"])
        q, k, v = jnp.split(qkv, [d_model, d_model + kv_d], axis=-1)
        att = attend(
            li,
            q.reshape(bsz, n_kv, group, hd),
            k.reshape(bsz, n_kv, hd),
            v.reshape(bsz, n_kv, hd),
        )
        h = h + att @ jnp.asarray(block["proj"])
        hx = _ln(h, block["ln2"])
        if "moe" in block:
            h = h + moe_ffn(block["moe"], hx[:, None, :], k=moe_top_k)[
                :, 0
            ]
        else:
            h = h + jax.nn.gelu(hx @ jnp.asarray(block["up"])) @ (
                jnp.asarray(block["down"])
            )
    return _ln(h, params["ln_f"]) @ embed.T


def transformer_prefill(params, tokens, moe_top_k: int = 1):
    """Batched causal prompt pass that also RETURNS the per-layer k/v in
    the decode-cache layout: ``tokens`` [B, P] ->
    ``(logits [B, P, vocab], k [L, B, n_kv, P, hd], v [L, B, n_kv, P, hd])``.

    This is the prefill half of serving decode: the whole prompt runs as
    dense MXU matmuls in one pass (instead of P sequential cache steps),
    and the caller scatters the returned k/v into its cache/page pool and
    continues with :func:`transformer_step`. Attention uses the same
    grouped-query einsum family as the step path."""
    import jax
    import jax.numpy as jnp

    from ..parallel.moe import moe_ffn

    tokens = jnp.asarray(tokens, dtype=jnp.int32)
    bsz, plen = tokens.shape
    n_heads = params["n_heads"]
    embed = jnp.asarray(params["embed"])
    posemb = jnp.asarray(params["pos"])
    d_model = embed.shape[1]
    hd = d_model // n_heads
    scale = 1.0 / float(np.sqrt(hd))
    neg = jnp.finfo(jnp.float32).min * 0.7
    causal = (
        jnp.arange(plen)[:, None] >= jnp.arange(plen)[None, :]
    )  # [P(q), P(k)]
    h = embed[tokens] + posemb[:plen][None]
    ks, vs = [], []
    for block in params["blocks"]:
        n_kv = _kv_heads(block, d_model, n_heads)
        group = n_heads // n_kv
        kv_d = n_kv * hd
        x = _ln(h, block["ln1"])
        qkv = x @ jnp.asarray(block["qkv"])
        q, k, v = jnp.split(qkv, [d_model, d_model + kv_d], axis=-1)
        # cache layout [B, n_kv, P, hd] — what the decode step reads
        kc = k.reshape(bsz, plen, n_kv, hd).transpose(0, 2, 1, 3)
        vc = v.reshape(bsz, plen, n_kv, hd).transpose(0, 2, 1, 3)
        ks.append(kc)
        vs.append(vc)
        qh = q.reshape(bsz, plen, n_kv, group, hd).transpose(0, 2, 3, 1, 4)
        s = jnp.einsum("bkgqd,bktd->bkgqt", qh, kc) * scale
        s = jnp.where(causal[None, None, None], s, neg)
        att = jnp.einsum("bkgqt,bktd->bkgqd", jax.nn.softmax(s, axis=-1), vc)
        att = att.transpose(0, 3, 1, 2, 4).reshape(bsz, plen, d_model)
        h = h + att @ jnp.asarray(block["proj"])
        hx = _ln(h, block["ln2"])
        if "moe" in block:
            h = h + moe_ffn(block["moe"], hx, k=moe_top_k)
        else:
            h = h + jax.nn.gelu(hx @ jnp.asarray(block["up"])) @ (
                jnp.asarray(block["down"])
            )
    logits = _ln(h, params["ln_f"]) @ embed.T
    return logits, jnp.stack(ks), jnp.stack(vs)


def transformer_prefill_chunk(params, tokens, positions, attend,
                              moe_top_k: int = 1):
    """One CHUNK of a prompt through the block walk, with attention
    delegated — the mid-sequence sibling of :func:`transformer_step`
    (single token, cache owned by the caller) and
    :func:`transformer_prefill` (whole prompt, dense causal, cache
    returned). Chunked prefill needs a third shape: a ``[B, C]`` span of
    tokens at arbitrary ``positions``, attending to cache the caller
    already holds (earlier chunks, or a shared-prefix hit) PLUS itself
    causally.

    ``tokens`` [B, C] int32; ``positions`` [C] int32 (absolute; the
    caller clips padding positions in-bounds). ``attend(li, q, k, v) ->
    [B, C, d_model]``: q ``[B, C, n_kv, group, hd]`` (grouped-query
    layout), this chunk's k/v ``[B, C, n_kv, hd]`` — the callback
    scatters k/v wherever it keeps its cache and reads the visible
    history under its own causal mask. The per-row math (LN, MLP,
    residuals, head split) is token-local and identical to
    :func:`transformer_prefill`'s, so a prompt prefilled in chunks
    produces byte-identical k/v and logits to one dense pass. Returns
    logits ``[B, C, vocab]``."""
    import jax.numpy as jnp

    tokens = jnp.asarray(tokens, dtype=jnp.int32)
    embed = jnp.asarray(params["embed"])
    posemb = jnp.asarray(params["pos"])
    h = embed[tokens] + posemb[positions][None]
    return _chunk_blocks(params, h, attend, moe_top_k)


def transformer_verify_chunk(params, tokens, positions, attend,
                             moe_top_k: int = 1):
    """The batched mid-sequence VERIFY step — the serving engine's
    speculative-decoding sibling of :func:`transformer_prefill_chunk`:
    the same delegated ``[B, C]`` block walk, but ``positions`` is
    ``[B, C]`` because every decode slot sits at its OWN absolute
    offset (slot ``b``'s ``k + 1`` verify tokens start at that
    sequence's pending position, not a shared chunk start). The per-row
    math is token-local and shared with the chunk walk
    (:func:`_chunk_blocks`), which is what makes a verify pass's
    logits — and therefore the target tokens sampled from them —
    byte-identical to the per-token decode step's at every position
    (docs/serving_llm.md "Speculative decoding"). Returns logits
    ``[B, C, vocab]``."""
    import jax.numpy as jnp

    tokens = jnp.asarray(tokens, dtype=jnp.int32)
    embed = jnp.asarray(params["embed"])
    posemb = jnp.asarray(params["pos"])
    h = embed[tokens] + posemb[positions]  # [B, C] positions -> [B, C, D]
    return _chunk_blocks(params, h, attend, moe_top_k)


def _chunk_blocks(params, h, attend, moe_top_k: int):
    """The shared ``[B, C]`` delegated-attention block walk of the
    chunk family (:func:`transformer_prefill_chunk` /
    :func:`transformer_verify_chunk`) — one implementation so the
    prefill-chunk and verify programs cannot drift apart."""
    import jax
    import jax.numpy as jnp

    from ..parallel.moe import moe_ffn

    bsz, clen, _ = h.shape
    n_heads = params["n_heads"]
    embed = jnp.asarray(params["embed"])
    d_model = embed.shape[1]
    hd = d_model // n_heads
    for li, block in enumerate(params["blocks"]):
        n_kv = _kv_heads(block, d_model, n_heads)
        group = n_heads // n_kv
        kv_d = n_kv * hd
        x = _ln(h, block["ln1"])
        qkv = x @ jnp.asarray(block["qkv"])
        q, k, v = jnp.split(qkv, [d_model, d_model + kv_d], axis=-1)
        att = attend(
            li,
            q.reshape(bsz, clen, n_kv, group, hd),
            k.reshape(bsz, clen, n_kv, hd),
            v.reshape(bsz, clen, n_kv, hd),
        )
        h = h + att @ jnp.asarray(block["proj"])
        hx = _ln(h, block["ln2"])
        if "moe" in block:
            h = h + moe_ffn(block["moe"], hx, k=moe_top_k)
        else:
            h = h + jax.nn.gelu(hx @ jnp.asarray(block["up"])) @ (
                jnp.asarray(block["down"])
            )
    return _ln(h, params["ln_f"]) @ embed.T


def transformer_tp_specs(params, axis: str = "tp"):
    """PartitionSpec tree for the TENSOR-PARALLEL SERVING weight layout
    (``params`` WITHOUT the ``n_heads`` entry — the device tree the
    serving engine ships): every large matrix is sharded AT REST along
    its hidden-ish axis — ``qkv`` and ``up`` on their output columns,
    ``proj`` and ``down`` on their input rows (= the MLP hidden dim) —
    while embeddings, positions, and layernorms stay replicated (the
    embedding is read by token lookup AND the tied head, both of which
    want full rows). Per-chip weight HBM shrinks ~1/N with the mesh.

    The compute plan (:mod:`tensorframes_tpu.serve.tp`) gathers these
    shards back to FULL weights inside each step program
    (:func:`gather_tp_params`) and runs every matmul at the solo
    program's exact shapes. That is deliberate: the serving contract is
    byte-identical decode streams at every TP degree, and neither
    Megatron row-parallel partial sums nor column-sliced GEMMs preserve
    float reduction order — an all-gathered shard tree, by contrast,
    reconstructs the solo weights bit-for-bit. The sharded COMPUTE lives
    where it is bit-exact by construction: the per-KV-head paged
    attention walk and the page pool, which are batch-indexed in the
    head axis. ``MoE`` blocks have no serving TP plan yet — rejected
    here so the error names the gap."""
    from jax.sharding import PartitionSpec as P

    rep = P()

    def ln_spec():
        return {"g": rep, "b": rep}

    blocks = []
    for i, block in enumerate(params["blocks"]):
        if "moe" in block:
            raise ValueError(
                f"block {i} is a mixture-of-experts block; tensor-"
                f"parallel serving shards dense blocks only (MoE serving "
                f"shards over an 'ep' mesh — not wired into the engine "
                f"yet)"
            )
        blocks.append(
            {
                "ln1": ln_spec(),
                "qkv": P(None, axis),
                "proj": P(axis, None),
                "ln2": ln_spec(),
                "up": P(None, axis),
                "down": P(axis, None),
            }
        )
    return {
        "embed": rep,
        "pos": rep,
        "ln_f": ln_spec(),
        "blocks": blocks,
    }


def gather_tp_params(p_loc, axis: str = "tp"):
    """Inside a ``shard_map`` body: all-gather the weight shards of
    :func:`transformer_tp_specs`'s layout back to FULL weights. Tiled
    gathers concatenate the shards in mesh order along the sharded axis,
    so the gathered tree is bit-for-bit the solo weight tree — the
    property the byte-identical-streams contract of
    :mod:`tensorframes_tpu.serve.tp` rides on."""
    import jax

    def g(a, ax):
        return jax.lax.all_gather(a, axis, axis=ax, tiled=True)

    blocks = [
        {
            **b,
            "qkv": g(b["qkv"], 1),
            "proj": g(b["proj"], 0),
            "up": g(b["up"], 1),
            "down": g(b["down"], 0),
        }
        for b in p_loc["blocks"]
    ]
    return {**p_loc, "blocks": blocks}


def transformer_generate(
    params: Params,
    prompt,
    max_new_tokens: int,
    temperature=0.0,
    seed=0,
    moe_top_k: int = 1,
    top_k: int = 0,
    top_p=1.0,
    prompt_lengths=None,
):
    """Autoregressive decode with a KV cache, compiled as ONE
    ``lax.scan`` program: per step the new token's q/k/v are computed,
    k/v land in a static-shape cache via ``dynamic_update_slice``, and
    attention reads the cache under a position mask — no recompilation
    per step, no growing shapes (the XLA-native decode loop; a Python
    loop re-running :func:`transformer_logits` on the growing sequence
    recompiles per length and recomputes O(L^2) work per token).

    ``temperature`` 0 = greedy argmax; > 0 samples categorically with a
    per-step key folded from ``seed``, after :func:`filter_logits` applies
    ``top_k`` / nucleus ``top_p`` truncation. ``temperature``, ``seed``
    and ``top_p`` may be TRACED scalars (pass them as jit arguments — one
    compiled program serves every seed/temperature sweep); ``top_k`` is
    static. Returns ``[B, P + max_new_tokens]`` int32 (prompt included).
    ``prompt + max_new_tokens`` must fit ``max_len`` (the positional
    table).

    Ragged batches: pass LEFT-padded prompts (each row's tokens at
    positions ``P-len..P-1``; :func:`left_pad_prompts` packs them) plus
    ``prompt_lengths`` [B]. Pad slots are excluded from attention and
    per-row position offsets keep the positional table aligned, so every
    row decodes exactly as it would alone; generation starts at the shared
    slot ``P`` for all rows."""
    import jax
    import jax.numpy as jnp

    prompt = jnp.asarray(prompt, dtype=jnp.int32)
    if prompt.ndim != 2 or prompt.shape[1] < 1:
        raise ValueError("prompt must be [B, P>=1] token ids")
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1; got {max_new_tokens}"
        )
    bsz, plen = prompt.shape
    n_heads = params["n_heads"]
    embed = jnp.asarray(params["embed"])
    posemb = jnp.asarray(params["pos"])
    d_model = embed.shape[1]
    hd = d_model // n_heads
    total = plen + max_new_tokens
    if total > posemb.shape[0]:
        raise ValueError(
            f"prompt ({plen}) + max_new_tokens ({max_new_tokens}) = "
            f"{total} exceeds max_len {posemb.shape[0]}"
        )
    blocks = params["blocks"]
    scale = 1.0 / float(np.sqrt(hd))
    neg = jnp.finfo(jnp.float32).min * 0.7
    # greedy vs sampled is a STRUCTURAL choice: concrete temperature <= 0
    # means greedy; a traced temperature always means the sampled program
    sampled = not (_is_concrete_scalar(temperature) and temperature <= 0)
    if prompt_lengths is None:
        offsets = jnp.zeros((bsz,), jnp.int32)
    else:
        offsets = plen - jnp.asarray(prompt_lengths, dtype=jnp.int32)

    # GQA: the cache stores only the model's n_kv k/v heads — the decode
    # memory ceiling shrinks by the group factor (n_kv == n_heads for MHA)
    n_kv = _kv_heads(blocks[0], d_model, n_heads)
    k0 = jnp.zeros((len(blocks), bsz, n_kv, total, hd), jnp.float32)
    v0 = jnp.zeros_like(k0)

    def step(carry, t):
        kc, vc, prev = carry
        tok = jnp.where(
            t < plen,
            jax.lax.dynamic_index_in_dim(
                prompt, jnp.minimum(t, plen - 1), axis=1, keepdims=False
            ),
            prev,
        )
        # visible = causal AND not a pad slot (slot j belongs to row b's
        # prompt iff j >= offsets[b])
        slots = jnp.arange(total)[None, :]
        visible = (slots <= t) & (slots >= offsets[:, None])  # [B, T]
        caches = [kc, vc]

        def attend(li, q, k, v):
            # grouped-query layout: q [B, n_kv, g, hd] against a cache
            # holding only n_kv k/v heads (g = 1 and n_kv = n_heads for
            # plain MHA — same math, same program shape). k/v land in the
            # scan-carried static-shape cache at slot t; attention reads
            # the whole cache under the visibility mask.
            caches[0] = jax.lax.dynamic_update_slice(
                caches[0], k.reshape(1, bsz, n_kv, 1, hd), (li, 0, 0, t, 0)
            )
            caches[1] = jax.lax.dynamic_update_slice(
                caches[1], v.reshape(1, bsz, n_kv, 1, hd), (li, 0, 0, t, 0)
            )
            s = jnp.einsum("bkgd,bktd->bkgt", q, caches[0][li]) * scale
            s = jnp.where(visible[:, None, None, :], s, neg)
            return jnp.einsum(
                "bkgt,bktd->bkgd", jax.nn.softmax(s, axis=-1), caches[1][li]
            ).reshape(bsz, d_model)

        # per-row position offset: a left-padded row's token at slot t sits
        # at real position t - offset (pad slots gather position 0; they
        # are masked out of attention above, so the value never matters)
        logits = transformer_step(
            params, tok, jnp.clip(t - offsets, 0, total - 1), attend,
            moe_top_k=moe_top_k,
        )
        kc, vc = caches
        if sampled:
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t)
            scaled = logits / jnp.maximum(
                jnp.asarray(temperature, jnp.float32), 1e-6
            )
            nxt = jax.random.categorical(
                key, filter_logits(scaled, top_k=top_k, top_p=top_p),
                axis=-1,
            )
        else:
            nxt = jnp.argmax(logits, axis=-1)
        nxt = nxt.astype(jnp.int32)
        return (kc, vc, nxt), nxt

    (_, _, _), outs = jax.lax.scan(
        step, (k0, v0, prompt[:, 0]), jnp.arange(total - 1)
    )
    # step t emits the prediction for position t+1: the generated tokens
    # are the emissions of steps plen-1 .. total-2 (with left padding,
    # every row's prompt ends at slot plen-1, so this holds for ragged
    # batches too)
    return jnp.concatenate([prompt, outs[plen - 1 :].T], axis=1)


def token_nll(
    params: Params, tokens, attn_impl: str = "reference", mesh=None,
    batch_axis=None, collect_moe_aux: bool = False, moe_top_k: int = 1,
    moe_impl: str = "masked", remat: bool = False,
):
    """Per-position next-token negative log-likelihood ``[B, L-1]`` — the
    one implementation both training loss and frame scoring reduce over.
    With ``collect_moe_aux`` returns ``(nll, aux)`` from the SAME forward
    (no second pass)."""
    import jax
    import jax.numpy as jnp

    fwd = transformer_logits(
        params, tokens[:, :-1], causal=True, attn_impl=attn_impl, mesh=mesh,
        batch_axis=batch_axis, collect_moe_aux=collect_moe_aux,
        moe_top_k=moe_top_k, moe_impl=moe_impl, remat=remat,
    )
    logits, aux = fwd if collect_moe_aux else (fwd, None)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(
        logp, targets[..., None].astype(jnp.int32), axis=-1
    )
    nll = -picked[..., 0]
    return (nll, aux) if collect_moe_aux else nll


def transformer_loss(
    params: Params, tokens, attn_impl: str = "reference", mesh=None,
    batch_axis=None, moe_aux_weight: float = 0.0, moe_top_k: int = 1,
    moe_impl: str = "masked", remat: bool = False,
):
    """Next-token cross entropy (mean over all predicted positions).

    ``moe_aux_weight`` > 0 adds the Switch load-balancing loss summed over
    the MoE blocks (typical value 1e-2) — the in-tree remedy for router
    collapse when training with ``moe_experts``."""
    if moe_aux_weight:
        nll, aux = token_nll(
            params, tokens, attn_impl=attn_impl, mesh=mesh,
            batch_axis=batch_axis, collect_moe_aux=True,
            moe_top_k=moe_top_k, moe_impl=moe_impl, remat=remat,
        )
        return nll.mean() + moe_aux_weight * aux
    return token_nll(
        params, tokens, attn_impl=attn_impl, mesh=mesh,
        batch_axis=batch_axis, moe_top_k=moe_top_k, moe_impl=moe_impl,
        remat=remat,
    ).mean()


@functools.lru_cache(maxsize=None)
def _pipe_stage_fn(n_heads: int):
    """Stable stage-function object per head count: the compiled pipeline
    program caches on FUNCTION IDENTITY (see ``parallel.pipeline``), so
    this must not be recreated per call. Delegates to the SAME block body
    the full-model path uses (:func:`_dense_block`)."""

    def fn(block, h):
        return _dense_block(block, h, n_heads)

    return fn


def _pipe_loss_fn(extra, y, targets):
    """Loss head fused into the pipeline's last stage (see
    :func:`_head_nll`)."""
    return _head_nll(extra["embed"], extra["ln_f"], y, targets)


class TransformerLM:
    """Parameter holder + frame scoring + simple SGD fitting."""

    def __init__(self, params: Params):
        self.params = params

    @staticmethod
    def init(seed: int, vocab: int, **kw) -> "TransformerLM":
        return TransformerLM(init_transformer(seed, vocab, **kw))

    def _sgd_loop(
        self, tokens, steps, lr, loss_kwargs, jit_kwargs=None, place=None,
        resume=None, checkpoint_every=None, on_step=None,
        place_restored=None,
    ):
        """Shared SGD machinery for :meth:`fit` and :meth:`fit_sharded`:
        jitted value_and_grad step, loop, params reassembly. ``loss_kwargs``
        feed :func:`transformer_loss`; ``jit_kwargs`` (e.g. out_shardings)
        configure the jit; ``place`` maps host tokens to device.

        ``resume``/``checkpoint_every``/``on_step``: same auto-resume
        contract as :meth:`ShardedSGDTrainer.fit <..parallel.training.ShardedSGDTrainer.fit>`
        — restore the latest step-numbered checkpoint from ``resume`` and
        continue, write every ``checkpoint_every`` steps and at the end
        (the reference rode Spark's task retry instead, SURVEY §5)."""
        import jax

        static = self.params["n_heads"]
        p = {k: v for k, v in self.params.items() if k != "n_heads"}

        def loss_fn(p_, toks_):
            return transformer_loss(
                {**p_, "n_heads": static}, toks_, **loss_kwargs
            )

        def step(p_, toks_):
            loss, grads = jax.value_and_grad(loss_fn)(p_, toks_)
            return jax.tree.map(lambda a, g: a - lr * g, p_, grads), loss

        step = jax.jit(step, **(jit_kwargs(p) if jit_kwargs else {}))
        toks = np.asarray(tokens, dtype=np.int32)
        if place is not None:
            toks = place(toks)
        from ..utils.checkpoint import run_checkpointed_loop

        p, losses = run_checkpointed_loop(
            lambda p_: step(p_, toks),
            p,
            steps,
            resume=resume,
            checkpoint_every=checkpoint_every,
            on_step=on_step,
            place_restored=place_restored,
        )
        self.params = {**jax.device_get(p), "n_heads": static}
        return losses

    def fit(
        self,
        tokens: np.ndarray,
        steps: int = 10,
        lr: float = 0.1,
        mesh=None,
        moe_aux_weight: float = 0.0,
        moe_top_k: int = 1,
        moe_impl: str = "masked",
        attn_impl: str = "reference",
        remat: bool = False,
        resume=None,
        checkpoint_every=None,
        on_step=None,
    ):
        """Jitted SGD on next-token loss. Single chip by default; pass a
        mesh with an ``ep`` axis to train MoE blocks expert-parallel
        (``moe_impl``: "masked" exact compute or "dispatch" Switch
        all-to-all), with ``moe_aux_weight`` adding the load-balancing
        loss. ``attn_impl="flash"`` trains through the pallas kernel's
        custom VJP (long context on one chip without the [L, L] matrix);
        sequence-parallel training lives in :meth:`fit_sharded`.
        ``resume``/``checkpoint_every``/``on_step``: auto-resume from a
        checkpoint directory (see :meth:`_sgd_loop`)."""
        kw = {}
        if mesh is not None:
            kw["mesh"] = mesh
        if moe_aux_weight:
            kw["moe_aux_weight"] = moe_aux_weight
        if moe_top_k != 1:
            kw["moe_top_k"] = moe_top_k
        if moe_impl != "masked":
            kw["moe_impl"] = moe_impl
        if attn_impl != "reference":
            kw["attn_impl"] = attn_impl
        if remat:
            kw["remat"] = True
        return self._sgd_loop(
            tokens, steps, lr, loss_kwargs=kw,
            resume=resume, checkpoint_every=checkpoint_every,
            on_step=on_step,
        )

    def fit_tp(
        self,
        tokens: np.ndarray,
        mesh,
        steps: int = 10,
        lr: float = 0.1,
        resume=None,
        checkpoint_every=None,
        on_step=None,
    ):
        """One jitted SGD step over a ``dp x tp`` mesh: batch rows sharded
        over ``dp``, every block's weights Megatron-sharded over ``tp`` —
        the MLP up-projection column-parallel (output dim), ``proj`` and
        the down-projection row-parallel (input dim), embeddings and
        layernorms replicated. No hand-written collectives: the shardings
        are GSPMD annotations, and XLA inserts the activation all-reduces
        after the row-parallel matmuls and the gradient all-reduces over
        both axes inside the SAME program (SURVEY §2.5 — the reference
        has no model parallelism at all). Training semantics are exactly
        the single-device step: losses match :meth:`fit` to float
        tolerance.

        The FUSED ``qkv`` matrix ([D, q|k|v], width ``d + 2*kv_d`` —
        ``3*d_model`` for plain MHA, smaller under GQA) is also
        output-sharded, but its tp cuts land at equal fractions of the
        fused width — across the q/k/v segment boundaries — so GSPMD
        inserts a reshard between the qkv matmul and the head split
        rather than the zero-comm Megatron column pattern (that would
        need per-segment sharding, i.e. separate q/k/v parameters).
        proj/up/down realize the classic pattern.

        Constraints: batch divisible by dp, ``n_heads`` and ``d_ff``
        divisible by tp (the head einsums partition on head boundaries).
        MoE blocks train expert-parallel via :meth:`fit`'s ``mesh``
        option instead; here their slabs are replicated."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if not {"dp", "tp"} <= set(mesh.axis_names):
            raise ValueError(
                f"fit_tp needs a mesh with 'dp' and 'tp' axes; got "
                f"{mesh.axis_names}"
            )
        n_heads = self.params["n_heads"]
        tp = mesh.shape["tp"]
        if n_heads % tp:
            raise ValueError(
                f"n_heads {n_heads} must divide by tp={tp} so the "
                f"column-parallel split lands on head boundaries"
            )
        d_model = int(np.shape(self.params["embed"])[1])
        for bl in self.params["blocks"]:
            n_kv = _kv_heads(bl, d_model, n_heads)
            if n_kv % tp:
                # with fewer kv heads than tp shards the k/v einsums
                # cannot partition on head boundaries and GSPMD silently
                # replicates/reshards k/v, eroding the Megatron pattern
                # (correct, but with extra collectives) — reject rather
                # than quietly train slow
                raise ValueError(
                    f"n_kv_heads {n_kv} must divide by tp={tp}: the k/v "
                    f"head einsums partition on kv-head boundaries (use "
                    f"tp <= n_kv_heads, or MHA weights)"
                )
        b = tokens.shape[0]
        if b % mesh.shape["dp"]:
            raise ValueError(
                f"batch {b} must divide by dp={mesh.shape['dp']}"
            )

        def sh(*spec):
            return NamedSharding(mesh, P(*spec))

        def block_shardings(block):
            s = {
                "ln1": {"g": sh(), "b": sh()},
                "qkv": sh(None, "tp"),
                "proj": sh("tp", None),
                "ln2": {"g": sh(), "b": sh()},
            }
            if "up" in block:
                if block["up"].shape[1] % tp:
                    raise ValueError(
                        f"d_ff {block['up'].shape[1]} must divide by "
                        f"tp={tp}"
                    )
                s["up"] = sh(None, "tp")
                s["down"] = sh("tp", None)
            if "moe" in block:
                s["moe"] = jax.tree.map(lambda _: sh(), block["moe"])
            return s

        pshard = {
            "embed": sh(),
            "pos": sh(),
            "ln_f": {"g": sh(), "b": sh()},
            "blocks": [
                block_shardings(bl) for bl in self.params["blocks"]
            ],
        }
        tok_sh = sh("dp", None)
        return self._sgd_loop(
            tokens,
            steps,
            lr,
            loss_kwargs={},
            jit_kwargs=lambda p_: dict(
                in_shardings=(pshard, tok_sh),
                out_shardings=(pshard, NamedSharding(mesh, P())),
            ),
            place=lambda t: jax.device_put(t, tok_sh),
            resume=resume,
            checkpoint_every=checkpoint_every,
            on_step=on_step,
            # restored leaves come back committed to one device; re-pin
            # them to the Megatron plan before the sharded step sees them
            place_restored=lambda p_: jax.device_put(p_, pshard),
        )

    def fit_sharded(
        self,
        tokens: np.ndarray,
        mesh,
        steps: int = 10,
        lr: float = 0.1,
        attn_impl: str = "ring",
        resume=None,
        checkpoint_every=None,
        on_step=None,
    ):
        """One jitted SGD step over a ``dp x sp`` mesh: batch rows sharded
        over ``dp``, attention sequence-parallel over ``sp`` — ``"ring"``
        (K/V rotation, any head count) or ``"ulysses"`` (two all_to_all
        transposes + the flash kernel's custom VJP; needs heads divisible
        by sp), both with ``batch_axis="dp"``. Both axes live in the SAME
        program, so GSPMD inserts the gradient all-reduce over dp around
        the sequence-parallel collectives over sp.

        Constraint from the loss shift: the attention runs on ``L - 1``
        positions, so ``tokens.shape[1] - 1`` must divide by the sp axis
        size (and the batch by the dp size)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if set(mesh.axis_names) != {"dp", "sp"}:
            raise ValueError(
                f"fit_sharded needs a ('dp','sp') mesh; got {mesh.axis_names}"
            )
        if attn_impl not in ("ring", "ulysses"):
            # both sequence-parallel impls train (flash_attention carries a
            # custom FlashAttention-2 VJP, so ulysses differentiates
            # through its pallas kernel); plain "flash"/"reference" keep
            # the sequence resident per chip, which contradicts the sp
            # sharding this path exists for
            raise ValueError(
                f"fit_sharded supports attn_impl='ring' or 'ulysses'; got "
                f"{attn_impl!r}"
            )
        b, length = tokens.shape
        if b % mesh.shape["dp"] or (length - 1) % mesh.shape["sp"]:
            raise ValueError(
                f"batch {b} must divide by dp={mesh.shape['dp']} and "
                f"L-1={length - 1} by sp={mesh.shape['sp']}"
            )
        rep = NamedSharding(mesh, P())
        return self._sgd_loop(
            tokens,
            steps,
            lr,
            loss_kwargs=dict(
                attn_impl=attn_impl, mesh=mesh, batch_axis="dp"
            ),
            jit_kwargs=lambda p: dict(
                out_shardings=(jax.tree.map(lambda _: rep, p), None)
            ),
            place=lambda t: jax.device_put(
                t, NamedSharding(mesh, P("dp", None))
            ),
            resume=resume,
            checkpoint_every=checkpoint_every,
            on_step=on_step,
            # params are replicated in this plan; re-pin restored
            # committed leaves so the dp/sp step sees one device set
            place_restored=lambda p_: jax.tree.map(
                lambda a: jax.device_put(a, rep), p_
            ),
        )

    def fit_pipelined(
        self,
        tokens: np.ndarray,
        mesh,
        steps: int = 10,
        lr: float = 0.1,
        n_micro: int = 4,
        schedule: str = "1f1b",
        grad_accum: int = 1,
        resume=None,
        checkpoint_every=None,
        on_step=None,
    ):
        """SGD with the transformer BLOCKS pipelined over the mesh's ``pp``
        axis (one block per chip), composed with data parallelism when the
        mesh has a ``dp`` axis (microbatch rows sharded over it).
        ``resume``/``checkpoint_every``/``on_step``: auto-resume from a
        checkpoint directory (see :meth:`_sgd_loop`) — the checkpointed
        tree is the PIPELINE layout (stacked, ``pp``-sharded blocks).

        The embedding runs outside the pipeline and trains through the
        returned input cotangent; the loss head (final norm + tied
        unembedding + cross entropy) is FUSED into the last stage's
        backward (:func:`..parallel.pipeline.pipeline_train_step`).
        ``schedule``: ``'1f1b'`` (bounded activation memory, recompute in
        backward) or ``'gpipe'`` (autodiff through the forward schedule).
        ``grad_accum`` splits the batch into that many sequential
        sub-batches whose grads are averaged before the update — the
        activation-memory knob beyond microbatching.

        Requires ``len(blocks) == mesh.shape['pp']``, dense (non-MoE)
        blocks, ``batch/grad_accum`` divisible by ``n_micro`` (and the
        microbatch by the ``dp`` size when present)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.pipeline import pipeline_train_step

        if "pp" not in mesh.axis_names:
            raise ValueError(
                f"fit_pipelined needs a mesh with a 'pp' axis; got "
                f"{mesh.axis_names}"
            )
        batch_axis = "dp" if "dp" in mesh.axis_names else None
        blocks = self.params["blocks"]
        if any("moe" in blk for blk in blocks):
            raise ValueError(
                "fit_pipelined supports dense blocks; MoE blocks train "
                "on an ep mesh (see parallel.moe)"
            )
        if len(blocks) != mesh.shape["pp"]:
            raise ValueError(
                f"{len(blocks)} blocks but pp={mesh.shape['pp']}; the "
                f"pipeline stages one block per chip"
            )
        toks = np.asarray(tokens, dtype=np.int32)
        b, length = toks.shape
        if b % grad_accum or (b // grad_accum) % n_micro:
            raise ValueError(
                f"batch {b} must divide by grad_accum={grad_accum} and "
                f"then by n_micro={n_micro}"
            )
        n_heads = self.params["n_heads"]
        stage_fn = _pipe_stage_fn(n_heads)
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
        stacked = jax.device_put(stacked, NamedSharding(mesh, P("pp")))
        p = {
            "embed": jnp.asarray(self.params["embed"]),
            "pos": jnp.asarray(self.params["pos"]),
            "ln_f": jax.tree.map(jnp.asarray, self.params["ln_f"]),
            "stacked": stacked,
        }
        sub = b // grad_accum
        Lm = length - 1

        def one_chunk(p_, chunk):
            ti = chunk[:, :-1]
            tgt = chunk[:, 1:]
            h0, h_vjp = jax.vjp(
                lambda e, po: e[ti] + po[:Lm][None], p_["embed"], p_["pos"]
            )
            loss, g_stacked, g_extra, dx = pipeline_train_step(
                stage_fn,
                _pipe_loss_fn,
                p_["stacked"],
                {"embed": p_["embed"], "ln_f": p_["ln_f"]},
                h0,
                tgt,
                n_micro=n_micro,
                mesh=mesh,
                batch_axis=batch_axis,
                schedule=schedule,
            )
            de_in, d_pos = h_vjp(dx)
            grads = {
                "embed": g_extra["embed"] + de_in,
                "pos": d_pos,
                "ln_f": g_extra["ln_f"],
                "stacked": g_stacked,
            }
            return loss, grads

        def step(p_, toks_):
            chunks = jnp.reshape(toks_, (grad_accum, sub, length))
            loss, grads = one_chunk(p_, chunks[0])
            for i in range(1, grad_accum):
                l2, g2 = one_chunk(p_, chunks[i])
                loss = loss + l2
                grads = jax.tree.map(jnp.add, grads, g2)
            inv = 1.0 / grad_accum
            new_p = jax.tree.map(
                lambda a, g: a - lr * (g * inv), p_, grads
            )
            return new_p, loss * inv

        step = jax.jit(step)

        def place_restored(p_):
            # restored leaves come back COMMITTED to a single device;
            # re-establish the pipeline placement (stacked slab over pp,
            # everything else replicated) or the jitted step sees mixed
            # device sets and refuses to compile
            rep = NamedSharding(mesh, P())
            return {
                "stacked": jax.device_put(
                    p_["stacked"], NamedSharding(mesh, P("pp"))
                ),
                **{
                    k: jax.tree.map(
                        lambda a: jax.device_put(a, rep), p_[k]
                    )
                    for k in ("embed", "pos", "ln_f")
                },
            }

        from ..utils.checkpoint import run_checkpointed_loop

        p, losses = run_checkpointed_loop(
            lambda p_: step(p_, toks),
            p,
            steps,
            resume=resume,
            checkpoint_every=checkpoint_every,
            on_step=on_step,
            place_restored=place_restored,
        )
        host = jax.device_get(p)
        n_layers = len(blocks)
        self.params = {
            "embed": host["embed"],
            "pos": host["pos"],
            "blocks": [
                jax.tree.map(lambda a: a[i], host["stacked"])
                for i in range(n_layers)
            ],
            "ln_f": host["ln_f"],
            "n_heads": n_heads,
        }
        return losses

    #: compiled decode programs kept per (shape, decode STRUCTURE); seeds,
    #: temperatures and top_p enter as traced arguments, so sweeps reuse
    #: one program. Bounded: oldest entry evicted beyond this.
    _GENERATE_CACHE_MAX = 16

    def generate(
        self,
        prompt,
        max_new_tokens: int,
        temperature: float = 0.0,
        seed: int = 0,
        moe_top_k: int = 1,
        top_k: int = 0,
        top_p: float = 1.0,
        prompt_lengths=None,
    ):
        """KV-cached autoregressive decode (:func:`transformer_generate`)
        as one jitted scan program, memoized per (prompt shape, decode
        STRUCTURE) in a bounded dict. The weights enter the program as an
        ARGUMENT, not as baked constants: a re-fit model reuses the same
        compiled program with its new params (nothing stale is pinned, no
        recompile). ``seed``, ``temperature`` and ``top_p`` are traced
        arguments too — sweeping them reuses ONE compiled program (greedy
        decodes ignore all three; they never enter the program).

        ``top_k`` / ``top_p`` truncate the sampling distribution (see
        :func:`filter_logits`). ``prompt_lengths`` enables ragged batches
        over LEFT-padded prompts (:func:`left_pad_prompts`)."""
        import jax

        prompt = np.asarray(prompt, dtype=np.int32)
        sampled = bool(temperature and temperature > 0)
        use_p = top_p is not None and top_p < 1.0
        ragged = prompt_lengths is not None
        if ragged:
            prompt_lengths = np.asarray(prompt_lengths, dtype=np.int32)
        key = (
            prompt.shape,
            int(max_new_tokens),
            sampled,
            int(top_k) if sampled else 0,
            use_p and sampled,
            int(moe_top_k),
            ragged,
        )
        cache = getattr(self, "_generate_cache", None)
        if cache is None:
            from collections import OrderedDict

            cache = self._generate_cache = OrderedDict()
        run = cache.get(key)
        if run is not None:
            cache.move_to_end(key)
        else:
            static = self.params["n_heads"]

            def impl(p, prompt_arr, seed_arr, temp_arr, top_p_arr, lens):
                return transformer_generate(
                    {**p, "n_heads": static},
                    prompt_arr,
                    max_new_tokens,
                    temperature=temp_arr if sampled else 0.0,
                    seed=seed_arr,
                    moe_top_k=moe_top_k,
                    top_k=top_k if sampled else 0,
                    top_p=top_p_arr if (sampled and use_p) else 1.0,
                    prompt_lengths=lens,
                )

            run = cache[key] = jax.jit(impl)
            while len(cache) > self._GENERATE_CACHE_MAX:
                cache.popitem(last=False)
        # one memoized device copy of the weights, replaced when fit
        # swaps the params object (the old copy is then collectable —
        # exactly one generation's weights are ever pinned)
        dev = getattr(self, "_generate_params", None)
        if dev is None or dev[0] is not self.params:
            host = {
                k: v for k, v in self.params.items() if k != "n_heads"
            }
            dev = self._generate_params = (
                self.params,
                jax.device_put(host),
            )
        return np.asarray(
            run(
                dev[1],
                prompt,
                np.int32(seed),
                np.float32(temperature if sampled else 0.0),
                np.float32(top_p if use_p else 1.0),
                prompt_lengths,
            )
        )

    def score_frame(
        self,
        df,
        col: str,
        loss_col: str = "nll",
        attn_impl: str = "reference",
        moe_top_k: int = 1,
        moe_impl: str = "masked",
    ):
        """Per-row next-token NLL appended as a column: the transformer
        version of frozen-graph scoring through ``map_blocks``.

        Routing is call-time config, not stored in params: a model
        trained with ``moe_top_k=2`` must be SCORED with ``moe_top_k=2``
        or each token gets only its argmax expert — a different network
        than was trained."""
        import jax.numpy as jnp

        from ..engine import map_blocks

        # capture needs the concrete [L] cell shape (positional embeddings
        # are length-dependent); analyze is O(1) for dense columns
        df = df.analyze()
        params = self.params

        def fn(**cols):
            toks = cols[col].astype(jnp.int32)
            return {
                loss_col: token_nll(
                    params, toks, attn_impl=attn_impl,
                    moe_top_k=moe_top_k, moe_impl=moe_impl,
                ).mean(axis=-1)
            }

        import inspect

        fn.__signature__ = inspect.Signature(
            [inspect.Parameter(col, inspect.Parameter.POSITIONAL_OR_KEYWORD)]
        )
        return map_blocks(fn, df)
