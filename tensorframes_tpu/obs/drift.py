"""Drift detection: EWMA baselines + tolerance bands over stored series.

The SLO monitors (:mod:`.slo`) answer "is this value ACCEPTABLE?"
against a bound an operator declared. Drift asks a different question:
"is this value still what it USED to be?" — no absolute bound, just a
learned baseline and a tolerance band around it. That is the trigger
feed ROADMAP item 4's re-tuning loop consumes ("the observatory records
drift nobody acts on"): an autotuner winner measured under last week's
traffic is stale exactly when the series it was tuned against drifts.

A :class:`Detector` watches one stored series (or every series under a
prefix) in the time-series store (:mod:`.timeseries`):

- the **baseline** is a deterministic EWMA over in-band samples,
  seeded by the first ``min_samples`` points (warmup: no banding);
- a sample is **out-of-band** when it falls outside
  ``baseline ± max(tolerance * |baseline|, min_band)``;
- ``trigger`` CONSECUTIVE out-of-band samples flip the series to
  **drifted** (``obs.drift_active{series}=1``, a
  ``drift``-ring flight event, a ``logger.warning``); ``trigger``
  consecutive in-band samples flip it back (recovery event, gauge 0);
- while any sample is out-of-band the baseline is FROZEN — a detector
  that kept averaging the shifted values would quietly adopt the drift
  as the new normal and report recovery without any recovery happening.
  The baseline resumes adapting only from in-band samples.

Everything is deterministic: same points in, same transitions out (the
drift e2e test replays a synthetic shift through ``sample_once`` ticks).
Evaluation rides the sampler tick next to SLO evaluation; the canned
default detectors cover the serving signals whose shifts most often
mean "re-tune or investigate": host→device p50, speculative acceptance
rate, inter-token p99, and the preemption rate. Cookbook:
``docs/observability.md`` ("Drift detection") and ``docs/tuning.md``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from . import flight as _flight
from .metrics import counter as _counter, enabled, gauge as _gauge

__all__ = [
    "Detector",
    "DriftMonitor",
    "default_detectors",
    "drift_report",
    "h2d_p50",
    "inter_token_p99",
    "monitor",
    "preemption_rate",
    "spec_acceptance",
]

logger = get_logger("obs.drift")

_m_shifts = _counter(
    "obs.drift_shifts_total",
    "Drift transitions (in-band -> drifted), by stored series",
    labels=("series",),
)
_g_active = _gauge(
    "obs.drift_active",
    "Whether the stored series is currently outside its EWMA baseline "
    "tolerance band (1) or tracking it (0)",
    labels=("series",),
)


@dataclasses.dataclass(frozen=True)
class Detector:
    """One drift rule over one stored series (or a name prefix).

    ``tolerance`` is RELATIVE (0.5 = ±50% of the baseline);
    ``min_band`` is the absolute band floor — essential for series that
    idle near zero (a preemption rate of 0.0 would otherwise make ANY
    preemption "drift"). ``match="prefix"`` resolves every stored
    series starting with ``series`` each tick, so labeled series
    (``failures.preemptions_total{op=serve}.rate``) are covered without
    naming each label combination."""

    name: str
    series: str
    tolerance: float = 0.5
    alpha: float = 0.1
    min_samples: int = 5
    trigger: int = 3
    min_band: float = 0.0
    match: str = "exact"

    def __post_init__(self):
        if self.match not in ("exact", "prefix"):
            raise ValueError(
                f"detector match must be 'exact' or 'prefix'; got "
                f"{self.match!r}"
            )
        if not 0.0 < self.alpha <= 1.0:
            raise ValueError(
                f"detector alpha must be in (0, 1]; got {self.alpha}"
            )
        if self.tolerance <= 0.0:
            raise ValueError(
                f"detector tolerance must be > 0; got {self.tolerance}"
            )
        if self.min_samples < 1 or self.trigger < 1:
            raise ValueError(
                "detector min_samples and trigger must be >= 1"
            )

    def band(self, baseline: float) -> float:
        return max(self.tolerance * abs(baseline), self.min_band)


class _State:
    """Per resolved-series detector state."""

    __slots__ = ("baseline", "n", "out_streak", "in_streak", "active",
                 "last_ts", "last_value", "since")

    def __init__(self):
        self.baseline: Optional[float] = None
        self.n = 0  # in-band samples folded into the baseline
        self.out_streak = 0
        self.in_streak = 0
        self.active = False
        self.last_ts = float("-inf")
        self.last_value: Optional[float] = None
        self.since: Optional[float] = None


class DriftMonitor:
    """Detector set + per-series drift state machine, evaluated per
    sampler tick. ``monitor()`` is the process-wide default (canned
    detectors preinstalled)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._detectors: Dict[str, Detector] = {}
        #: (detector name, resolved series) -> state
        self._states: Dict[Tuple[str, str], _State] = {}

    def add(self, detector: Detector) -> Detector:
        with self._lock:
            self._detectors[detector.name] = detector
        return detector

    def remove(self, name: str) -> None:
        with self._lock:
            self._detectors.pop(name, None)
            gone = [k for k in self._states if k[0] == name]
            for k in gone:
                self._states.pop(k)
        for _, series in gone:
            _g_active.set(0.0, series=series)

    def detectors(self) -> List[Detector]:
        with self._lock:
            return list(self._detectors.values())

    def _resolve(self, det: Detector, store) -> List[str]:
        if det.match == "exact":
            return [det.series]
        return [n for n in store.names() if n.startswith(det.series)]

    # -- evaluation --------------------------------------------------------

    def evaluate(self, store, now: Optional[float] = None) -> None:
        """One pass: feed every detector the points that landed since
        its last evaluation (by timestamp — deterministic under replay).
        Called by ``timeseries.sample_once`` after the tick's points
        land."""
        if not enabled():
            return
        ts_now = time.time() if now is None else now
        for det in self.detectors():
            for series in self._resolve(det, store):
                with self._lock:
                    st = self._states.setdefault(
                        (det.name, series), _State()
                    )
                for pt_ts, value in store.points(series, 0):
                    if pt_ts <= st.last_ts:
                        continue
                    st.last_ts = pt_ts
                    self._feed(det, series, st, pt_ts, value)

    def _feed(
        self, det: Detector, series: str, st: _State,
        ts: float, value: float,
    ) -> None:
        st.last_value = value
        if st.baseline is None:
            st.baseline = value
            st.n = 1
            return
        if st.n < det.min_samples:
            # warmup: the baseline absorbs everything, no banding yet
            st.baseline += det.alpha * (value - st.baseline)
            st.n += 1
            return
        out = abs(value - st.baseline) > det.band(st.baseline)
        if out:
            st.out_streak += 1
            st.in_streak = 0
            # baseline frozen: adapting to out-of-band samples would
            # adopt the shift as the new normal (see module doc)
        else:
            st.in_streak += 1
            st.out_streak = 0
            st.baseline += det.alpha * (value - st.baseline)
        if out and not st.active and st.out_streak >= det.trigger:
            st.active = True
            st.since = ts
            _m_shifts.inc(series=series)
            _g_active.set(1.0, series=series)
            delta = value - st.baseline
            logger.warning(
                "drift %r: series %s shifted to %g (baseline %g, "
                "band ±%g)",
                det.name, series, value, st.baseline,
                det.band(st.baseline),
            )
            _flight.record(
                "drift", "shift",
                detector=det.name, series=series, value=value,
                baseline=round(st.baseline, 6),
                band=round(det.band(st.baseline), 6),
                delta=round(delta, 6),
            )
        elif not out and st.active and st.in_streak >= det.trigger:
            st.active = False
            dur = ts - st.since if st.since is not None else None
            st.since = None
            _g_active.set(0.0, series=series)
            logger.warning(
                "drift %r: series %s recovered (drifted %.1fs)",
                det.name, series, dur or 0.0,
            )
            _flight.record(
                "drift", "recovered",
                detector=det.name, series=series, value=value,
                baseline=round(st.baseline, 6),
                drifted_s=None if dur is None else round(dur, 3),
            )

    # -- reporting ---------------------------------------------------------

    def report(self) -> List[Dict[str, Any]]:
        """One row per (detector, resolved series) that has seen data —
        what ``drift_report()``, the ``/statusz`` ``drift`` table, and
        the re-tune loop read. ``delta`` is last value minus baseline
        (signed: which WAY it drifted)."""
        out = []
        with self._lock:
            dets = dict(self._detectors)
            items = list(self._states.items())
        for (dname, series), st in items:
            det = dets.get(dname)
            if det is None:
                continue
            delta = (
                None
                if st.last_value is None or st.baseline is None
                else st.last_value - st.baseline
            )
            out.append({
                "detector": dname,
                "series": series,
                "active": st.active,
                "since": st.since,
                "baseline": st.baseline,
                "last_value": st.last_value,
                "delta": delta,
                "band": (
                    None if st.baseline is None
                    else det.band(st.baseline)
                ),
                "samples": st.n,
            })
        out.sort(key=lambda r: (not r["active"], r["series"]))
        return out

    def any_active(self) -> bool:
        with self._lock:
            return any(s.active for s in self._states.values())

    def reset(self) -> None:
        with self._lock:
            keys = list(self._states)
            self._states.clear()
        for _, series in keys:
            _g_active.set(0.0, series=series)


# -- canned detectors ---------------------------------------------------------


def h2d_p50(**kw) -> Detector:
    """Host→device transfer p50 — a shifted link (new tunnel, congested
    fabric) invalidates the transfer chunk/stream tuning."""
    return Detector(
        name="h2d_p50", series="frame.h2d_seconds.p50", **kw,
    )


def spec_acceptance(**kw) -> Detector:
    """Speculative-decoding acceptance rate, any engine — the draft
    length was tuned against THIS rate; a drifted workload wants a new
    ``spec_k``."""
    kw.setdefault("match", "prefix")
    kw.setdefault("tolerance", 0.25)
    return Detector(
        name="spec_acceptance", series="serve.spec_acceptance_rate",
        **kw,
    )


def inter_token_p99(**kw) -> Detector:
    """Decode-cadence p99 — the serving latency signal users feel."""
    return Detector(
        name="inter_token_p99", series="serve.inter_token_seconds.p99",
        **kw,
    )


def preemption_rate(**kw) -> Detector:
    """Preemptions/second, any op label. ``min_band`` floors the band:
    the healthy baseline is ~0/s, and a relative band around zero would
    flag the first preemption ever as drift."""
    kw.setdefault("match", "prefix")
    kw.setdefault("min_band", 0.5)
    return Detector(
        name="preemption_rate",
        series="failures.preemptions_total", **kw,
    )


def default_detectors() -> List[Detector]:
    return [h2d_p50(), spec_acceptance(), inter_token_p99(),
            preemption_rate()]


_monitor = DriftMonitor()
for _det in default_detectors():
    _monitor.add(_det)
del _det


def monitor() -> DriftMonitor:
    """The process-wide default monitor (what the sampler tick
    evaluates and ``/statusz`` reports)."""
    return _monitor


def drift_report() -> List[Dict[str, Any]]:
    """Convenience: :meth:`DriftMonitor.report` on the default
    monitor — the queryable answer to "what drifted, and by how
    much?"."""
    return _monitor.report()
