"""Time-series store: the registry's history, sampled on a cadence.

The metrics registry (:mod:`.metrics`) is deliberately point-in-time —
a scrape sees *now* and nothing else, so every question that needs a
window ("what was queue depth over the last minute?", "did TTFT p99
move when the fleet fenced r1?") has so far required an external
Prometheus. The ROADMAP's autoscaler (item 5) and the SLO monitors
(:mod:`.slo`) both need those windows **in-process**. This module is
that store:

- a background **sampler** (one daemon thread, cadence
  ``Config.obs_sample_interval_s``, re-read each tick) walks the
  default registry and appends one point per live series:

  =========  ======================  =================================
  metric     series name             point
  =========  ======================  =================================
  gauge      ``<name>{l=v}``         the gauge value
  counter    ``<name>{l=v}.rate``    per-second rate since last tick
  histogram  ``<name>.p50`` / ``.p99``  bucket quantiles of the
                                     observations since the LAST tick
                                     (windowed — a latency spike ages
                                     out, so SLOs over these recover;
                                     idle ticks record no point)
  histogram  ``<name>.rate``         observations/second since last tick
  =========  ======================  =================================

- each series is a bounded **ring with downsampled retention tiers**:
  tier 0 holds the newest ``samples_per_tier`` raw points; every
  ``downsample`` tier-0 appends collapse (mean value, last timestamp)
  into one tier-1 point, and so on — three tiers at the defaults
  (512 samples, ×8) cover 512 s / ~68 min / ~9 h of history at a 1 s
  cadence in ~12 KB per series;
- queries merge tiers transparently: :meth:`TimeSeriesStore.window`
  returns the best-resolution points covering the asked span;
- ``GET /varz`` on the serving port (``interop/serving.py``) exports
  the store as JSON, so operators and the autoscaler see real series
  without running a Prometheus.

The sampler also drives the two consumers that want a heartbeat: SLO
evaluation (:mod:`.slo`) and the program-cost registry's JSONL
persistence (:mod:`.programs`) ride the same tick, so one thread owns
every periodic observability duty.

Lifecycle is refcounted: every ``ScoringServer.start()`` acquires the
sampler and ``stop()`` releases it; tests and benches call
:func:`acquire_sampler` / :func:`release_sampler` directly (or
:func:`sample_once` for deterministic single ticks). Kill-switch
parity: with ``TFT_OBS=0`` / ``Config(observability=False)`` a tick
records nothing.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    counter as _counter,
    enabled,
    gauge as _gauge,
    quantile_from_counts,
    registry,
)

__all__ = [
    "TimeSeriesStore",
    "acquire_sampler",
    "last_tick_ts",
    "release_sampler",
    "sample_once",
    "sampler_running",
    "store",
]

logger = get_logger("obs.timeseries")

_m_ticks = _counter(
    "obs.ts_samples_total",
    "Completed time-series sampler ticks (one registry walk each)",
)
_g_series = _gauge(
    "obs.ts_series",
    "Series currently tracked by the in-process time-series store",
)
_g_lag = _gauge(
    "obs.ts_sampler_lag_seconds",
    "Gap between the last two completed sampler ticks — a stalled, "
    "leaked, or overloaded sampler is itself detectable here (the "
    "live now-minus-last-tick lag is derived on /varz)",
)

#: histogram quantiles snapshotted per tick, as (suffix, q)
_QUANTILES: Tuple[Tuple[str, float], ...] = (("p50", 0.5), ("p99", 0.99))

#: a runaway label space (e.g. a per-request label someone adds later)
#: must exhaust the store's series budget, not the process's memory
_MAX_SERIES = 4096


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, "") or default)
    except ValueError:
        return default
    return v if v > 0 else default


class _Ring:
    """One retention tier: a fixed-capacity ring of ``(ts, value)``."""

    __slots__ = ("cap", "data", "start", "count")

    def __init__(self, cap: int):
        self.cap = cap
        self.data: List[Optional[Tuple[float, float]]] = [None] * cap
        self.start = 0  # index of the OLDEST point
        self.count = 0

    def append(self, ts: float, value: float) -> None:
        if self.count < self.cap:
            self.data[(self.start + self.count) % self.cap] = (ts, value)
            self.count += 1
        else:  # wraparound: overwrite the oldest
            self.data[self.start] = (ts, value)
            self.start = (self.start + 1) % self.cap

    def points(self) -> List[Tuple[float, float]]:
        """Oldest-first copy."""
        return [
            self.data[(self.start + i) % self.cap]  # type: ignore[misc]
            for i in range(self.count)
        ]


class _Series:
    """One named series: tier 0 raw, higher tiers downsampled by
    ``factor`` each — an append cascades a (mean, last-ts) point up one
    tier every ``factor`` appends at the tier below."""

    __slots__ = ("name", "tiers", "factor", "_acc_sum", "_acc_n")

    def __init__(self, name: str, cap: int, factor: int, n_tiers: int):
        self.name = name
        self.tiers = [_Ring(cap) for _ in range(n_tiers)]
        self.factor = factor
        #: per-tier downsample accumulators (sum, n) feeding tier i+1
        self._acc_sum = [0.0] * (n_tiers - 1)
        self._acc_n = [0] * (n_tiers - 1)

    def append(self, ts: float, value: float) -> None:
        self.tiers[0].append(ts, value)
        for t in range(len(self.tiers) - 1):
            self._acc_sum[t] += value
            self._acc_n[t] += 1
            if self._acc_n[t] < self.factor:
                break
            value = self._acc_sum[t] / self._acc_n[t]
            self._acc_sum[t] = 0.0
            self._acc_n[t] = 0
            self.tiers[t + 1].append(ts, value)


class TimeSeriesStore:
    """Bounded in-process history for every live registry series.

    ``sample(now)`` is one tick (the background sampler calls it; tests
    call it directly); ``window(name, seconds)`` / ``latest(name)`` are
    the query surface the SLO monitors and ``/varz`` read."""

    def __init__(
        self,
        samples_per_tier: Optional[int] = None,
        downsample: Optional[int] = None,
        tiers: int = 3,
    ):
        self._cap = samples_per_tier or _env_int("TFT_OBS_TS_SAMPLES", 512)
        self._factor = downsample or _env_int("TFT_OBS_TS_DOWNSAMPLE", 8)
        self._tiers = max(1, int(tiers))
        self._series: Dict[str, _Series] = {}
        self._lock = threading.Lock()
        #: serializes whole ticks: the background sampler and an
        #: explicit sample_once() caller must not interleave their
        #: read-modify-writes of the rate/histogram baselines below (a
        #: torn baseline records a spurious near-zero rate point, which
        #: a floor-SLO would count as a violation)
        self._sample_lock = threading.Lock()
        #: counter/histogram-count snapshots from the previous tick, for
        #: rate derivation: series name -> (ts, cumulative value)
        self._last_cum: Dict[str, Tuple[float, float]] = {}
        #: histogram bucket snapshots from the previous tick, for the
        #: WINDOWED per-tick quantiles: series name -> (counts, count)
        self._last_hist: Dict[str, Tuple[List[int], int]] = {}
        self._dropped = False
        #: wall-clock timestamp of the last completed tick (None before
        #: the first) — the sampler's own liveness signal: /varz shows
        #: it and derives the current lag from it
        self._last_tick_ts: Optional[float] = None

    # -- recording ---------------------------------------------------------

    def record(self, name: str, ts: float, value: float) -> None:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                if len(self._series) >= _MAX_SERIES:
                    if not self._dropped:
                        self._dropped = True
                        logger.warning(
                            "time-series store is full (%d series); new "
                            "series are dropped — a label explosion "
                            "upstream?", _MAX_SERIES,
                        )
                    return
                s = self._series[name] = _Series(
                    name, self._cap, self._factor, self._tiers
                )
            s.append(ts, float(value))

    def _rate(self, name: str, ts: float, cum: float) -> None:
        """Record a per-second rate point derived from a cumulative
        value. The first sighting establishes the baseline (no point);
        a counter reset (value went DOWN — process restart semantics)
        re-baselines instead of recording a negative rate."""
        prev = self._last_cum.get(name)
        self._last_cum[name] = (ts, cum)
        if prev is None:
            return
        pts, pv = prev
        dt = ts - pts
        if dt <= 0 or cum < pv:
            return
        self.record(name, ts, (cum - pv) / dt)

    def sample(self, now: Optional[float] = None) -> int:
        """One tick over the default registry; returns points recorded.
        No-op (0) when observability is off."""
        if not enabled():
            return 0
        with self._sample_lock:
            return self._sample_locked(now)

    def _sample_locked(self, now: Optional[float]) -> int:
        ts = time.time() if now is None else now
        reg = registry()
        recorded = 0
        for mname in reg.names():
            try:
                m = reg.get(mname)
            except KeyError:
                continue
            series = m._series()
            if isinstance(m, Gauge):
                for key, v in series.items():
                    self.record(_series_name(mname, m.label_names, key), ts, v)
                    recorded += 1
            elif isinstance(m, Counter):
                for key, v in series.items():
                    self._rate(
                        _series_name(mname, m.label_names, key) + ".rate",
                        ts, v,
                    )
                    recorded += 1
            elif isinstance(m, Histogram):
                for key, s in series.items():
                    if not s["count"]:
                        continue
                    base = _series_name(mname, m.label_names, key)
                    # quantiles over the DELTA since the last tick, not
                    # the lifetime buckets: cumulative quantiles never
                    # decay, so a one-minute latency spike would pin an
                    # all-time p99 over any SLO bound for hours after
                    # the incident ended. Windowed, the spike ages out
                    # of the stored series with the spike itself (the
                    # first sighting baselines; idle ticks record no
                    # point; a reset re-baselines like counter rates).
                    prev = self._last_hist.get(base)
                    self._last_hist[base] = (
                        list(s["counts"]), s["count"],
                    )
                    if prev is not None:
                        pc, pn = prev
                        dn = s["count"] - pn
                        delta = [
                            a - b for a, b in zip(s["counts"], pc)
                        ]
                        if dn > 0 and all(d >= 0 for d in delta):
                            for suffix, q in _QUANTILES:
                                qv = quantile_from_counts(
                                    m.bounds, delta, dn, q
                                )
                                if qv is not None:
                                    self.record(
                                        f"{base}.{suffix}", ts, qv
                                    )
                                    recorded += 1
                    self._rate(base + ".rate", ts, float(s["count"]))
        _g_series.set(float(len(self._series)))
        if self._last_tick_ts is not None:
            _g_lag.set(max(0.0, ts - self._last_tick_ts))
        self._last_tick_ts = ts
        _m_ticks.inc()
        return recorded

    # -- querying ----------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def points(self, name: str, tier: int = 0) -> List[Tuple[float, float]]:
        """One tier's points for ``name``, oldest first ([] if absent)."""
        with self._lock:
            s = self._series.get(name)
            if s is None or not 0 <= tier < len(s.tiers):
                return []
            return s.tiers[tier].points()

    def window(
        self, name: str, seconds: float, now: Optional[float] = None
    ) -> List[Tuple[float, float]]:
        """Points within the trailing ``seconds``, best resolution
        first-served: tier 0 covers the newest span; where the window
        reaches past tier 0's oldest point, older tiers fill in with
        their downsampled points. Oldest first."""
        ts_now = time.time() if now is None else now
        lo = ts_now - seconds
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            out: List[Tuple[float, float]] = []
            covered_from = float("inf")  # walk tiers fine -> coarse
            for ring in s.tiers:
                pts = ring.points()
                if pts:
                    older = [
                        p for p in pts if lo <= p[0] < covered_from
                    ]
                    out = older + out
                    covered_from = min(covered_from, pts[0][0])
                if covered_from <= lo:
                    break
        return out

    def latest(self, name: str) -> Optional[Tuple[float, float]]:
        pts = self.points(name, 0)
        return pts[-1] if pts else None

    def to_dict(
        self,
        prefix: Optional[str] = None,
        window_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The ``/varz`` payload: every (matching) series with its raw
        tier-0 points (or a merged window when ``window_s`` is given)
        and per-tier depths."""
        names = [
            n for n in self.names() if not prefix or n.startswith(prefix)
        ]
        out: Dict[str, Any] = {}
        for n in names:
            pts = (
                self.window(n, window_s)
                if window_s is not None
                else self.points(n, 0)
            )
            with self._lock:
                s = self._series.get(n)
                depths = [r.count for r in s.tiers] if s is not None else []
            out[n] = {
                "points": [[round(ts, 3), v] for ts, v in pts],
                "tiers": depths,
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._last_cum.clear()
            self._last_hist.clear()
            self._dropped = False
            self._last_tick_ts = None


_store = TimeSeriesStore()


def store() -> TimeSeriesStore:
    """The process-wide default store (what ``/varz`` and the SLO
    monitors read)."""
    return _store


def last_tick_ts() -> Optional[float]:
    """Wall-clock timestamp of the default store's last completed tick
    (``None`` before the first) — ``/varz`` derives the live sampler
    lag from it, and telemetry snapshots carry it."""
    return _store._last_tick_ts


def sample_once(now: Optional[float] = None) -> int:
    """One deterministic sampler tick against the default store,
    including the piggybacked duties (SLO evaluation, drift detection,
    program-registry persistence, telemetry export — export last, so a
    snapshot sees this tick's drift gauges) — what the background
    thread runs on its cadence."""
    n = _store.sample(now)
    try:
        from . import slo as _slo

        _slo.monitor().evaluate(_store, now=now)
    except Exception:
        logger.warning("SLO evaluation failed", exc_info=True)
    try:
        from . import drift as _drift

        _drift.monitor().evaluate(_store, now=now)
    except Exception:
        logger.warning("drift evaluation failed", exc_info=True)
    try:
        from . import programs as _programs

        _programs.autopersist()
    except Exception:
        logger.warning("program-registry persistence failed", exc_info=True)
    try:
        # SLO-actuated QoS: act on this tick's freshly-evaluated burn
        # state (shed/deprioritize/recover). Only when the tenancy
        # module is ALREADY imported — a serving process has it via the
        # scheduler; a batch-only sampler must not drag the serve stack
        # (and jax programs) in just to tick a no-op hook.
        import sys as _sys

        _tenancy = _sys.modules.get("tensorframes_tpu.serve.tenancy")
        if _tenancy is not None:
            _tenancy.slo_tick(now=now)
    except Exception:
        logger.warning("tenancy SLO tick failed", exc_info=True)
    try:
        from . import export as _export

        _export.autoexport(now=now)
    except Exception:
        logger.warning("telemetry export failed", exc_info=True)
    return n


# -- background sampler ------------------------------------------------------

_sampler_lock = threading.Lock()
_sampler_refs = 0
_sampler_thread: Optional[threading.Thread] = None
#: the CURRENT thread's stop event — each started thread captures its
#: own (a release->acquire bounce must not clear the event the old
#: thread is waiting on, or the old thread never exits and two
#: samplers tick concurrently)
_sampler_stop = threading.Event()


def _sampler_loop(stop_evt: threading.Event) -> None:
    from ..utils.config import get_config

    while not stop_evt.is_set():
        interval = get_config().obs_sample_interval_s
        if interval <= 0:
            # parked: poll the knob at a slow fixed cadence
            stop_evt.wait(0.5)
            continue
        t0 = time.monotonic()
        try:
            sample_once()
        except Exception:
            logger.warning("sampler tick failed", exc_info=True)
        # fixed cadence, not fixed sleep: a slow tick does not stretch
        # the series' spacing more than it must
        stop_evt.wait(max(0.01, interval - (time.monotonic() - t0)))


def acquire_sampler() -> None:
    """Refcounted start of the background sampler thread. Every
    ``acquire`` must be paired with a :func:`release_sampler`; the
    thread exists while the count is positive. (``ScoringServer``
    acquires on ``start()`` and releases on ``stop()``.)"""
    global _sampler_refs, _sampler_thread, _sampler_stop
    with _sampler_lock:
        _sampler_refs += 1
        if _sampler_thread is None or not _sampler_thread.is_alive():
            stop_evt = threading.Event()
            _sampler_stop = stop_evt
            _sampler_thread = threading.Thread(
                target=_sampler_loop, args=(stop_evt,),
                name="tft-obs-sampler", daemon=True,
            )
            _sampler_thread.start()


def release_sampler() -> None:
    global _sampler_refs, _sampler_thread
    with _sampler_lock:
        if _sampler_refs == 0:
            return
        _sampler_refs -= 1
        if _sampler_refs > 0:
            return
        _sampler_stop.set()
        thread = _sampler_thread
        _sampler_thread = None
    if thread is not None:
        thread.join(timeout=5)


def sampler_running() -> bool:
    with _sampler_lock:
        t = _sampler_thread
        return t is not None and t.is_alive()


def _series_name(
    metric: str, label_names: Sequence[str], key: Tuple[str, ...]
) -> str:
    """Stored-series name for one labeled metric series. The label part
    delegates to the registry's own ``_label_str`` so snapshot keys and
    stored-series names can never drift apart — the SLO presets (e.g.
    ``serve.requests_total{status=failed}.rate``) match on this exact
    format."""
    from .metrics import _label_str

    if not key:
        return metric
    return f"{metric}{{{_label_str(label_names, key)}}}"
