"""Telemetry aggregation: merge per-process snapshots into a fleet view.

The read side of the fleet telemetry plane (:mod:`.export` is the write
side). :func:`read_snapshots` loads every valid ``*.json`` under the
telemetry directory; :func:`merge` combines any set of snapshots into
one fleet-wide view; :func:`fleet_status` is the memoized top-level API
mirroring ``engine.dist_jobs.journal_status`` (read-only, any process,
cheap to poll — ``GET /varz?scope=fleet`` and the ``/statusz`` fleet
block call it per request).

Merge rules — the part that must be EXACT, not approximate:

- **counters** sum per labeled series across processes (monotonic
  totals add);
- **gauges** keep per-process values plus ``sum`` and ``max`` — neither
  reduction alone is right for every gauge (queue depths sum, a
  utilization gauge wants max), so the fleet view keeps both and the
  per-proc breakdown;
- **histograms** merge by BUCKET COUNTS: every process uses the same
  fixed bounds (``metrics.DEFAULT_BUCKETS`` — fixed "so series from
  different processes always merge bucket-for-bucket"), so elementwise
  count addition gives exactly the histogram a single process observing
  the union would hold, and :func:`~.metrics.quantile_from_counts` over
  the merged counts is bucket-exact — identical to the oracle over the
  combined observations. Mismatched bounds (a cross-version process)
  keep the first process's data and flag ``"mixed_buckets"`` rather
  than silently adding apples to oranges;
- **time series** align by tick: points from different processes are
  bucketed to the integer second; ``.rate`` series (per-second rates
  derived from counters) SUM within a tick, everything else (gauges,
  quantiles) takes the mean, and each merged series lists the
  contributing procs;
- **staleness** is flagged, never dropped: a process whose snapshot
  file stopped refreshing (mtime older than
  ``Config.telemetry_stale_after_s``) stays in the view with
  ``stale: true`` — a kill -9'd worker's last counters remain visible.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from .export import SCHEMA_VERSION
from .metrics import quantile_from_counts

__all__ = [
    "fleet_status",
    "merge",
    "read_snapshots",
]

logger = get_logger("obs.aggregate")


def read_snapshots(dir: str) -> List[Dict[str, Any]]:
    """Every valid snapshot under ``dir``, sorted by proc id. Tolerant
    by design: torn/corrupt files (a reader racing a non-atomic writer
    — cannot happen with :mod:`.export` but the directory is shared),
    foreign schemas, and non-snapshot JSON are skipped with a debug log,
    never raised — one bad file must not blind the whole pane."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(dir))
    except OSError:
        return out
    for fname in names:
        if not fname.endswith(".json"):
            continue
        path = os.path.join(dir, fname)
        try:
            mtime = os.stat(path).st_mtime
            with open(path) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            logger.debug("skipping unreadable snapshot %s", path)
            continue
        if (
            not isinstance(snap, dict)
            or snap.get("schema") != SCHEMA_VERSION
            or "proc" not in snap
        ):
            logger.debug("skipping foreign snapshot %s", path)
            continue
        snap["_mtime"] = mtime
        out.append(snap)
    out.sort(key=lambda s: str(s.get("proc")))
    return out


def _merge_counter(dst: Dict[str, float], values: Dict[str, Any]) -> None:
    for ls, v in values.items():
        try:
            dst[ls] = dst.get(ls, 0.0) + float(v)
        except (TypeError, ValueError):
            continue


def _merge_gauge(
    dst: Dict[str, Dict[str, float]], proc: str, values: Dict[str, Any]
) -> None:
    for ls, v in values.items():
        try:
            dst.setdefault(ls, {})[proc] = float(v)
        except (TypeError, ValueError):
            continue


def _merge_histogram(
    entry: Dict[str, Any], buckets: List[float], values: Dict[str, Any]
) -> None:
    if entry.get("buckets") is None:
        entry["buckets"] = list(buckets)
    elif list(buckets) != entry["buckets"]:
        entry["mixed_buckets"] = True
        return
    dst = entry["values"]
    for ls, s in values.items():
        try:
            counts = [int(c) for c in s["counts"]]
            ssum, scount = float(s["sum"]), int(s["count"])
        except (KeyError, TypeError, ValueError):
            continue
        cur = dst.get(ls)
        if cur is None:
            dst[ls] = {"counts": counts, "sum": ssum, "count": scount}
        elif len(cur["counts"]) == len(counts):
            cur["counts"] = [a + b for a, b in zip(cur["counts"], counts)]
            cur["sum"] += ssum
            cur["count"] += scount


def merge(
    snapshots: List[Dict[str, Any]],
    now: Optional[float] = None,
    stale_after_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Combine snapshots into one fleet view (rules in the module doc).

    Returns ``{"procs": [...], "metrics": {...}, "series": {...}}``.
    ``procs`` rows carry identity + ``age_s`` + ``stale`` (mtime-based,
    threshold ``stale_after_s`` / ``Config.telemetry_stale_after_s``);
    merged histogram values gain exact ``p50``/``p99``."""
    ts_now = time.time() if now is None else now
    if stale_after_s is None:
        from ..utils.config import get_config

        stale_after_s = get_config().telemetry_stale_after_s

    procs: List[Dict[str, Any]] = []
    metrics: Dict[str, Dict[str, Any]] = {}
    series_acc: Dict[str, Dict[int, List[float]]] = {}
    series_procs: Dict[str, set] = {}

    for snap in snapshots:
        proc = str(snap.get("proc"))
        ident = snap.get("identity") or {}
        age = ts_now - snap.get("_mtime", snap.get("ts_unix", ts_now))
        procs.append({
            "proc": proc,
            "pid": snap.get("pid"),
            "role": ident.get("role", "unknown"),
            "version": ident.get("version", "unknown"),
            "device": ident.get("device", "unknown"),
            "host": ident.get("host"),
            "ts_unix": snap.get("ts_unix"),
            "age_s": round(age, 3),
            "stale": age > stale_after_s,
        })
        for name, m in (snap.get("metrics") or {}).items():
            if not isinstance(m, dict) or "type" not in m:
                continue
            entry = metrics.setdefault(name, {
                "type": m["type"],
                "help": m.get("help", ""),
                "labels": m.get("labels", []),
                "values": {},
                "per_proc": {} if m["type"] == "gauge" else None,
            })
            if entry["type"] != m["type"]:
                entry["mixed_types"] = True
                continue
            values = m.get("values") or {}
            if m["type"] == "counter":
                _merge_counter(entry["values"], values)
            elif m["type"] == "gauge":
                _merge_gauge(entry["per_proc"], proc, values)
            elif m["type"] == "histogram":
                _merge_histogram(
                    entry, m.get("buckets") or [], values
                )
        for name, pts in (snap.get("series") or {}).items():
            acc = series_acc.setdefault(name, {})
            series_procs.setdefault(name, set()).add(proc)
            for p in pts:
                try:
                    pts_ts, v = float(p[0]), float(p[1])
                except (TypeError, ValueError, IndexError):
                    continue
                acc.setdefault(int(pts_ts), []).append(v)

    # finalize gauges (sum/max alongside the per-proc breakdown) and
    # histogram quantiles (bucket-exact over the merged counts)
    for name, entry in metrics.items():
        if entry["type"] == "gauge":
            for ls, by_proc in entry["per_proc"].items():
                vals = list(by_proc.values())
                entry["values"][ls] = {
                    "sum": sum(vals),
                    "max": max(vals),
                    "procs": dict(by_proc),
                }
            entry.pop("per_proc")
        else:
            entry.pop("per_proc", None)
            if entry["type"] == "histogram":
                bounds = entry.get("buckets") or []
                for ls, s in entry["values"].items():
                    for suffix, q in (("p50", 0.5), ("p99", 0.99)):
                        s[suffix] = quantile_from_counts(
                            bounds, s["counts"], s["count"], q
                        )

    series: Dict[str, Any] = {}
    for name, acc in series_acc.items():
        rate_like = name.endswith(".rate")
        pts = []
        for tick in sorted(acc):
            vals = acc[tick]
            v = sum(vals) if rate_like else sum(vals) / len(vals)
            pts.append([float(tick), v])
        series[name] = {
            "points": pts,
            "procs": sorted(series_procs[name]),
            "merge": "sum" if rate_like else "mean",
        }

    return {"procs": procs, "metrics": metrics, "series": series}


# -- memoized top-level API ---------------------------------------------------

#: dir -> (stamp, parsed snapshots); the PARSE is memoized on the
#: directory's (fname, mtime_ns, size) stamp — the merge itself is
#: recomputed per call because staleness is a function of *now*, not of
#: the files (the journal_status memo in engine/dist_jobs.py splits
#: static-vs-live state the same way)
_status_cache: Dict[str, Tuple[Tuple, List[Dict[str, Any]]]] = {}
_status_cache_lock = threading.Lock()
_STATUS_CACHE_MAX = 8


def _dir_stamp(dir: str) -> Tuple:
    try:
        entries = []
        for fname in os.listdir(dir):
            if not fname.endswith(".json"):
                continue
            try:
                st = os.stat(os.path.join(dir, fname))
                entries.append((fname, st.st_mtime_ns, st.st_size))
            except OSError:
                continue
        return tuple(sorted(entries))
    except OSError:
        return ()


def fleet_status(
    dir: str,
    now: Optional[float] = None,
    stale_after_s: Optional[float] = None,
) -> Dict[str, Any]:
    """One-call fleet view over a telemetry directory — the
    ``journal_status`` of the telemetry plane: read-only, callable from
    any process, cheap to poll (snapshot parsing is memoized on the
    directory's mtime stamp; only the time-dependent merge reruns)."""
    stamp = _dir_stamp(dir)
    with _status_cache_lock:
        hit = _status_cache.get(dir)
    if hit is not None and hit[0] == stamp:
        snaps = hit[1]
    else:
        snaps = read_snapshots(dir)
        with _status_cache_lock:
            if len(_status_cache) >= _STATUS_CACHE_MAX and dir not in (
                _status_cache
            ):
                _status_cache.pop(next(iter(_status_cache)))
            _status_cache[dir] = (stamp, snaps)
    out = merge(snaps, now=now, stale_after_s=stale_after_s)
    out["dir"] = dir
    return out
