"""Per-program cost registry: what every compiled program costs, forever.

Compiled programs are this engine's unit of spend — the graph-memo
programs of ``engine/ops.py``, the ≤ 3 serving step programs of
``serve/engine.py``, and the fused plan composites of
``engine/plan.py`` — yet until now nothing recorded what any of them
cost to build or to run: the bench trajectory measures end-to-end
passes, and the future autotuner (ROADMAP item 3, the learned-cost-model
line: Kaufman et al. arXiv:2008.01040, TpuGraphs arXiv:2308.13490)
needs exactly the per-program (features → cost) pairs that were being
thrown away. This registry keeps them:

- every instrumented program registers ONE :class:`ProgramRecord` at
  build time: **compile wall-time** (the first dispatch, which pays
  trace + XLA compile), **FLOP / byte estimates** — XLA's own
  ``Lowered.cost_analysis()`` where available, with a jaxpr-walking
  fallback (:func:`jaxpr_costs`) — and a free-form ``meta`` of
  shape/dtype features;
- every later dispatch accumulates **invocation count + cumulative
  dispatch wall-time** (a ~1 µs wrapper; with ``TFT_OBS=0`` the wrapper
  is a pass-through). Programs whose call sites do not synchronize
  (the batch engine's pipelined chunk dispatches) accumulate *enqueue*
  wall — an understatement on async backends, exact on the synced
  serving steps;
- :func:`table` derives the **roofline view**: achieved FLOP/s over the
  dispatched time, arithmetic intensity (FLOPs/byte), and utilization
  against the device's peak (:func:`peak_flops` — known TPU
  generations, or the ``TFT_PEAK_FLOPS`` / ``TFT_PEAK_BYTES_PER_S``
  overrides; ``None`` on hosts with no table entry, e.g. CPU). It is
  what ``GET /statusz`` serves and ``explain(analyze=True)`` renders;
- :func:`persist` appends the records as JSONL next to the batch-job
  journal root (``<job root>/programs.jsonl``, or
  ``TFT_PROGRAM_COSTS_FILE``), so the r01→r05 bench trajectory gains
  per-program ground truth across processes; the time-series sampler
  (:mod:`.timeseries`) calls the throttled :func:`autopersist` on its
  tick.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from .metrics import enabled, gauge as _gauge

__all__ = [
    "ProgramRecord",
    "autopersist",
    "costs_path",
    "estimate_costs",
    "instrument",
    "jaxpr_costs",
    "peak_bytes_per_s",
    "peak_flops",
    "persist",
    "program",
    "programs",
    "reset",
    "table",
]

logger = get_logger("obs.programs")

_g_registered = _gauge(
    "obs.programs_registered",
    "Compiled programs currently tracked by the cost registry",
)

_lock = threading.Lock()
_records: Dict[str, "ProgramRecord"] = {}
_last_persist = 0.0
#: bound on registry size — a pathological caller minting a program per
#: request must saturate, not leak
_MAX_PROGRAMS = 4096


class ProgramRecord:
    """One compiled program's ledger entry."""

    __slots__ = (
        "key", "name", "kind", "created_ts", "compile_s", "flops",
        "bytes_accessed", "cost_source", "invocations", "dispatches",
        "dispatch_s", "last_dispatch_ts", "meta", "_lock", "_persisted_inv",
    )

    def __init__(self, key: str, name: str, kind: str, **meta):
        self.key = key
        self.name = name
        self.kind = kind
        self.created_ts = time.time()
        self.compile_s: Optional[float] = None
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.cost_source: Optional[str] = None  # "xla" | "jaxpr"
        self.invocations = 0
        #: dispatches EXCLUDING the compile-paying first call — the
        #: denominator pair for the roofline (flops * dispatches /
        #: dispatch_s)
        self.dispatches = 0
        self.dispatch_s = 0.0
        self.last_dispatch_ts: Optional[float] = None
        self.meta: Dict[str, Any] = dict(meta)
        self._lock = threading.Lock()
        self._persisted_inv = -1  # autopersist dirtiness watermark

    # -- accumulation ------------------------------------------------------

    def note_compile(self, seconds: float) -> None:
        with self._lock:
            self.invocations += 1
            self.last_dispatch_ts = time.time()
            if self.compile_s is None:
                self.compile_s = seconds
            else:  # a second signature recompiled under the same record
                self.compile_s += seconds

    def add_dispatch(self, seconds: float) -> None:
        with self._lock:
            self.invocations += 1
            self.dispatches += 1
            self.dispatch_s += seconds
            self.last_dispatch_ts = time.time()

    def set_costs(
        self, flops: Optional[float], bytes_accessed: Optional[float],
        source: Optional[str],
    ) -> None:
        with self._lock:
            self.flops = flops
            self.bytes_accessed = bytes_accessed
            self.cost_source = source

    # -- derived view ------------------------------------------------------

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            d: Dict[str, Any] = {
                "key": self.key,
                "name": self.name,
                "kind": self.kind,
                "compile_s": _round(self.compile_s),
                "flops": self.flops,
                "bytes": self.bytes_accessed,
                "cost_source": self.cost_source,
                "invocations": self.invocations,
                "dispatches": self.dispatches,
                "dispatch_s": _round(self.dispatch_s),
                "meta": dict(self.meta),
            }
            flops, disp, dt = self.flops, self.dispatches, self.dispatch_s
            bytes_ = self.bytes_accessed
        achieved = (
            flops * disp / dt if flops and disp and dt > 0 else None
        )
        d["achieved_flops_per_s"] = _round(achieved)
        d["intensity_flops_per_byte"] = _round(
            flops / bytes_ if flops and bytes_ else None
        )
        peak = peak_flops()
        d["roofline_utilization"] = _round(
            achieved / peak if achieved and peak else None
        )
        return d


def _round(v: Optional[float], digits: int = 6) -> Optional[float]:
    return None if v is None else round(float(v), digits)


def program(key: str, name: str, kind: str, **meta) -> ProgramRecord:
    """Get-or-create the record for ``key`` (idempotent — the build-time
    registration point)."""
    with _lock:
        rec = _records.get(key)
        if rec is None:
            if len(_records) >= _MAX_PROGRAMS:
                # saturated: hand back a detached record so callers keep
                # working; it simply is not listed
                return ProgramRecord(key, name, kind, **meta)
            rec = _records[key] = ProgramRecord(key, name, kind, **meta)
            _g_registered.set(float(len(_records)))
        return rec


def programs() -> List[ProgramRecord]:
    with _lock:
        return list(_records.values())


def table() -> List[Dict[str, Any]]:
    """Every program's ledger row, heaviest (cumulative dispatch time)
    first — the ``/statusz`` programs table."""
    rows = [r.as_dict() for r in programs()]
    rows.sort(key=lambda r: (-(r["dispatch_s"] or 0.0), r["name"]))
    return rows


def reset() -> None:
    """Drop every record (test isolation)."""
    global _last_persist
    with _lock:
        _records.clear()
        _last_persist = 0.0
    _g_registered.set(0.0)


def render_table() -> str:
    """Plain-text programs table for ``explain(analyze=True)``."""
    rows = table()
    if not rows:
        return "== Programs ==\n (no compiled programs registered)"
    lines = ["== Programs =="]
    for r in rows:
        util = r["roofline_utilization"]
        lines.append(
            f" {r['name']} [{r['kind']}] "
            f"compile={_fmt_s(r['compile_s'])} "
            f"flops={_fmt_num(r['flops'])} "
            f"bytes={_fmt_num(r['bytes'])} "
            f"inv={r['invocations']} "
            f"dispatch={_fmt_s(r['dispatch_s'])} "
            f"achieved={_fmt_num(r['achieved_flops_per_s'])}F/s "
            + (f"roofline={util:.2%}" if util is not None else "roofline=n/a")
        )
    return "\n".join(lines)


def _fmt_s(v: Optional[float]) -> str:
    return "n/a" if v is None else f"{v:.4f}s"


def _fmt_num(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}"
    return f"{v:.0f}"


# ---------------------------------------------------------------------------
# cost estimation
# ---------------------------------------------------------------------------


def estimate_costs(
    fn, *args, **kwargs
) -> Tuple[Optional[float], Optional[float], Optional[str]]:
    """``(flops, bytes, source)`` for one program at one signature.

    Tries XLA's analysis off the jit's ``lower()`` artifact first (no
    compile — lowering only), then falls back to walking the jaxpr
    (:func:`jaxpr_costs`). ``(None, None, None)`` when both fail — cost
    accounting must never break a dispatch."""
    try:
        lowered = fn.lower(*args, **kwargs)
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):  # per-device list on older APIs
            ca = ca[0] if ca else {}
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed")
        if flops is not None or nbytes is not None:
            return (
                float(flops) if flops is not None else None,
                float(nbytes) if nbytes is not None else None,
                "xla",
            )
    except Exception:
        pass
    try:
        import jax

        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        flops, nbytes = jaxpr_costs(closed)
        return flops, nbytes, "jaxpr"
    except Exception:
        return None, None, None


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def _aval_size(v) -> int:
    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _eqn_flops(eqn) -> float:
    """FLOPs for one jaxpr equation — exact for ``dot_general`` (2MNK),
    kernel-shaped for convolutions, operand-sized for reductions,
    output-sized for everything else (the elementwise approximation).
    Inner jaxprs (pjit / scan / while / custom derivatives / remat)
    recurse; ``scan`` multiplies by its trip count."""
    prim = eqn.primitive.name
    params = eqn.params
    if prim == "dot_general":
        (lhs_contract, _), _ = params["dimension_numbers"]
        out_size = sum(_aval_size(v) for v in eqn.outvars)
        lhs = eqn.invars[0].aval
        k = 1
        for ax in lhs_contract:
            k *= int(lhs.shape[ax])
        return 2.0 * out_size * k
    if prim == "conv_general_dilated":
        out = eqn.outvars[0].aval
        rhs = eqn.invars[1].aval
        dn = params.get("dimension_numbers")
        out_feature_axis = dn.out_spec[1] if dn is not None else 1
        out_channels = max(int(out.shape[out_feature_axis]), 1)
        rhs_size = 1
        for d in rhs.shape:
            rhs_size *= int(d)
        return 2.0 * _aval_size(out) * (rhs_size / out_channels)
    inner = params.get("jaxpr") or params.get("call_jaxpr")
    if inner is not None:
        body = getattr(inner, "jaxpr", inner)
        flops = _jaxpr_flops(body)
        if prim == "scan":
            flops *= max(int(params.get("length", 1)), 1)
        return flops
    if params.get("body_jaxpr") is not None:  # while: one iteration
        f = _jaxpr_flops(params["body_jaxpr"].jaxpr)
        if params.get("cond_jaxpr") is not None:
            f += _jaxpr_flops(params["cond_jaxpr"].jaxpr)
        return f
    if prim == "cond":
        return max(
            (
                _jaxpr_flops(b.jaxpr)
                for b in params.get("branches", ())
            ),
            default=0.0,
        )
    if prim.startswith(("reduce_", "arg")) or prim in ("cumsum", "cumprod"):
        return float(sum(_aval_size(v) for v in eqn.invars))
    return float(sum(_aval_size(v) for v in eqn.outvars))


def _jaxpr_flops(jaxpr) -> float:
    return float(sum(_eqn_flops(e) for e in jaxpr.eqns))


def jaxpr_costs(closed_jaxpr) -> Tuple[float, float]:
    """``(flops, bytes)`` from a closed jaxpr: FLOPs summed over
    equations (see :func:`_eqn_flops`), bytes as program inputs +
    outputs + consts — the memory-traffic LOWER bound the roofline
    wants (intermediates that stay in registers/cache are not link
    traffic)."""
    jaxpr = closed_jaxpr.jaxpr
    nbytes = float(
        sum(_aval_bytes(v) for v in jaxpr.invars)
        + sum(_aval_bytes(v) for v in jaxpr.outvars)
        + sum(_aval_bytes(c) for c in closed_jaxpr.consts)
    )
    return _jaxpr_flops(jaxpr), nbytes


# ---------------------------------------------------------------------------
# device peaks (roofline denominators)
# ---------------------------------------------------------------------------

#: per-chip dense matmul peaks (bf16, FLOP/s) by device-kind prefix —
#: the roofline denominator when no TFT_PEAK_FLOPS override is set.
#: Hosts not listed (CPU, unknown TPUs) report utilization = n/a.
_TPU_PEAK_FLOPS = (
    ("TPU v6", 918e12),
    ("TPU v5p", 459e12),
    ("TPU v5", 197e12),  # v5e / "TPU v5 lite"
    ("TPU v4", 275e12),
    ("TPU v3", 123e12),
    ("TPU v2", 45e12),
)
_TPU_PEAK_BYTES = (
    ("TPU v6", 1640e9),
    ("TPU v5p", 2765e9),
    ("TPU v5", 819e9),
    ("TPU v4", 1228e9),
    ("TPU v3", 900e9),
    ("TPU v2", 700e9),
)


def _device_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return ""


def _peak(env: str, tbl) -> Optional[float]:
    override = os.environ.get(env, "")
    if override:
        try:
            return float(override)
        except ValueError:
            logger.warning("malformed %s=%r ignored", env, override)
    kind = _device_kind()
    for prefix, v in tbl:
        if kind.startswith(prefix):
            return v
    return None


def peak_flops() -> Optional[float]:
    """This host's peak FLOP/s for roofline utilization:
    ``TFT_PEAK_FLOPS`` override, else the known-TPU table, else ``None``
    (utilization renders as n/a — honest on CPU hosts)."""
    return _peak("TFT_PEAK_FLOPS", _TPU_PEAK_FLOPS)


def peak_bytes_per_s() -> Optional[float]:
    """Peak memory bandwidth (``TFT_PEAK_BYTES_PER_S`` override, else
    the known-TPU HBM table, else ``None``)."""
    return _peak("TFT_PEAK_BYTES_PER_S", _TPU_PEAK_BYTES)


# ---------------------------------------------------------------------------
# the dispatch wrapper
# ---------------------------------------------------------------------------


class InstrumentedProgram:
    """Transparent callable around a jitted program: the first enabled
    call registers the :class:`ProgramRecord` and records compile
    wall-time + cost estimates; every later call accumulates invocation
    + dispatch wall-time. Registration is LAZY so that with the kill
    switch on (``TFT_OBS=0``) wrapping and calling leave the registry —
    and the persisted JSONL — completely untouched (``record`` stays
    ``None``). Attribute access (``.lower`` for ``precompile``)
    delegates to the wrapped jit."""

    __slots__ = (
        "_fn", "_sync", "_estimated", "record", "_key", "_name",
        "_kind", "_meta", "_cache_size",
    )

    def __init__(self, fn, key: str, name: str, kind: str, meta, sync):
        self._fn = fn
        self._sync = sync
        self._estimated = False
        self.record: Optional[ProgramRecord] = None
        self._key = key
        self._name = name
        self._kind = kind
        self._meta = meta
        #: the jit's executable-cache depth at our last look: a call
        #: that GREW it paid a trace+compile, so its wall belongs in
        #: compile_s, not dispatch_s — booking a later-signature
        #: recompile (map_rows' final partial chunk, a new padded
        #: prefill width) as a dispatch would poison achieved-FLOP/s
        #: with seconds of compile wall
        self._cache_size = -1

    def __call__(self, *args, **kwargs):
        if not enabled():
            return self._fn(*args, **kwargs)
        rec = self.record
        if rec is None:
            rec = self.record = program(
                self._key, self._name, self._kind, **self._meta
            )
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        if self._sync:
            import jax

            out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        try:
            size = self._fn._cache_size()
        except Exception:
            size = None
        if size is None:  # no cache introspection: first call only
            compiled = not self._estimated
        else:
            compiled = size != self._cache_size
            self._cache_size = size
        if compiled:
            rec.note_compile(dt)
            if not self._estimated:
                # first observed call: its args pin the signature the
                # cost estimate describes
                self._estimated = True
                flops, nbytes, source = estimate_costs(
                    self._fn, *args, **kwargs
                )
                rec.set_costs(flops, nbytes, source)
        else:
            rec.add_dispatch(dt)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def instrument(
    fn, *, key: str, name: str, kind: str, sync: bool = False, **meta
) -> InstrumentedProgram:
    """Wrap a jitted callable so its costs land in the registry.

    ``sync=True`` blocks on the outputs inside the timing window —
    correct only where the call site synchronizes anyway (the serving
    step dispatches); pipelined call sites (the batch engine's chunk
    loops) keep ``sync=False`` and accumulate enqueue wall."""
    return InstrumentedProgram(fn, key, name, kind, meta, sync)


# ---------------------------------------------------------------------------
# persistence
# ---------------------------------------------------------------------------


def costs_path() -> str:
    """Where program records persist: ``TFT_PROGRAM_COSTS_FILE``, else
    ``programs.jsonl`` next to the batch-job journal root
    (``Config.job_dir`` / ``$TFT_JOB_DIR`` /
    ``~/.cache/tensorframes_tpu/jobs``) — the same trajectory directory
    the bench artifacts and journals live in, so the autotuner's
    training data accumulates in one place."""
    explicit = os.environ.get("TFT_PROGRAM_COSTS_FILE", "")
    if explicit:
        return explicit
    from ..utils.config import get_config

    root = (
        get_config().job_dir
        or os.environ.get("TFT_JOB_DIR")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "tensorframes_tpu", "jobs"
        )
    )
    return os.path.join(root, "programs.jsonl")


def persist(path: Optional[str] = None) -> int:
    """Append one JSONL line per record whose stats moved since the
    last persist; returns lines written. Failures log and return 0 —
    cost accounting must never take down the path it measures."""
    try:
        target = path or costs_path()
        dirty: List[Tuple[ProgramRecord, int]] = []
        for rec in programs():
            with rec._lock:
                if rec.invocations != rec._persisted_inv:
                    dirty.append((rec, rec.invocations))
        if not dirty:
            return 0
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
        ts = time.time()
        host, pid = socket.gethostname(), os.getpid()
        with open(target, "a") as f:
            for rec, _ in dirty:
                row = rec.as_dict()
                row.update(ts=round(ts, 3), host=host, pid=pid)
                f.write(json.dumps(row, default=str) + "\n")
        # watermarks advance only AFTER the write landed: a failed
        # write (disk full, read-only path) must leave the records
        # dirty so the next successful persist still captures their
        # final state — that state is the autotuner's training data
        for rec, inv in dirty:
            with rec._lock:
                rec._persisted_inv = inv
        return len(dirty)
    except Exception:
        logger.warning("program-cost persist failed", exc_info=True)
        return 0


#: minimum seconds between autopersist writes (the sampler calls it
#: every tick; disk sees it at most this often)
_AUTOPERSIST_S = 30.0


def autopersist() -> int:
    """Throttled :func:`persist` for the sampler tick. No-op under the
    kill switch — TFT_OBS=0 must never touch the disk."""
    global _last_persist
    if not enabled():
        return 0
    now = time.monotonic()
    if now - _last_persist < _AUTOPERSIST_S:
        return 0
    _last_persist = now
    return persist()
