"""SLO monitors: declarative objectives over the time-series store.

Metrics say what IS; an SLO says what is ACCEPTABLE — and the gap
between the two is what pages an operator and what an autoscaler acts
on. An :class:`Objective` names one bound over one stored series
(:mod:`.timeseries`):

>>> from tensorframes_tpu.obs import slo
>>> slo.monitor().add(slo.ttft_p99(0.5))           # TTFT p99 <= 500 ms
>>> slo.monitor().add(slo.tokens_per_s_floor(200)) # emission floor
>>> slo.monitor().add(slo.queue_depth_ceiling(32))
>>> slo.monitor().add(slo.error_rate_ceiling(0.5)) # failed req/s

Evaluation rides the sampler tick (``timeseries.sample_once``) and uses
the standard two-window **burn-rate** shape: the *fast* window (default
60 s) measures the fraction of recent samples violating the bound —
responsive, catches a sharp breach within seconds — and the *slow*
window (default 300 s) measures the same over a longer span, separating
a blip from a sustained burn. An objective **breaches** when the fast
window's violation fraction reaches ``burn_threshold`` (default 0.5)
with at least ``min_samples`` points; while also past the threshold on
the slow window the breach is ``severity="sustained"``, else
``"fast"``.

Breach/recovery transitions emit flight-recorder events (the ``slo``
ring) and count into ``slo.breaches_total{slo}``; the live state is the
``slo.breached{slo}`` gauge, the ``/statusz`` ``slo`` table, and the
``/healthz`` ``status`` field — ``"degraded"`` (still HTTP 200: the
replica serves, but it is violating its objectives) as a state DISTINCT
from ``"unhealthy"`` (503: the engine cannot serve at all). Cookbook:
``docs/observability.md``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import get_logger
from . import flight as _flight
from .metrics import counter as _counter, gauge as _gauge

__all__ = [
    "Objective",
    "SLOMonitor",
    "error_rate_ceiling",
    "monitor",
    "queue_depth_ceiling",
    "tokens_per_s_floor",
    "ttft_p99",
]

logger = get_logger("obs.slo")

_m_breaches = _counter(
    "slo.breaches_total",
    "SLO breach transitions (ok -> breached), by objective",
    labels=("slo",),
)
_g_breached = _gauge(
    "slo.breached",
    "Whether the objective is currently breached (1) or ok (0)",
    labels=("slo",),
)


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective over one stored time series.

    ``kind="upper"``: a sample violates when ``value > bound`` (latency
    bounds, depth ceilings); ``kind="lower"``: when ``value < bound``
    (throughput floors)."""

    name: str
    series: str
    bound: float
    kind: str = "upper"
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_threshold: float = 0.5
    min_samples: int = 3
    #: treat exact-0.0 samples as "no traffic" and exclude them from
    #: the burn computation. Counter-rate series record an explicit
    #: 0.0 every tick while idle (by design — the autoscaler wants to
    #: see idleness), so a throughput FLOOR over one would otherwise
    #: breach on a healthy idle server. On by default for
    #: :func:`tokens_per_s_floor`; a stalled-but-demanded server is the
    #: queue-depth ceiling's job (the queue grows while the rate sits
    #: at 0). Set False to alert on idleness itself.
    ignore_zero: bool = False

    def __post_init__(self):
        if self.kind not in ("upper", "lower"):
            raise ValueError(
                f"objective kind must be 'upper' or 'lower'; got "
                f"{self.kind!r}"
            )
        if not 0.0 < self.burn_threshold <= 1.0:
            raise ValueError(
                f"burn_threshold must be in (0, 1]; got "
                f"{self.burn_threshold}"
            )
        if self.slow_window_s < self.fast_window_s:
            raise ValueError(
                "slow_window_s must be >= fast_window_s "
                f"({self.slow_window_s} < {self.fast_window_s})"
            )

    def violates(self, value: float) -> bool:
        return value > self.bound if self.kind == "upper" else (
            value < self.bound
        )


class _State:
    __slots__ = ("breached", "since", "severity", "fast_burn", "slow_burn",
                 "last_value", "samples")

    def __init__(self):
        self.breached = False
        self.since: Optional[float] = None
        self.severity: Optional[str] = None
        self.fast_burn = 0.0
        self.slow_burn = 0.0
        self.last_value: Optional[float] = None
        self.samples = 0


class SLOMonitor:
    """Objective set + breach state machine, evaluated per sampler
    tick. ``monitor()`` is the process-wide default the serving
    endpoints read."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objectives: Dict[str, Objective] = {}
        self._states: Dict[str, _State] = {}

    def add(self, objective: Objective) -> Objective:
        with self._lock:
            self._objectives[objective.name] = objective
            self._states.setdefault(objective.name, _State())
        return objective

    def remove(self, name: str) -> None:
        with self._lock:
            self._objectives.pop(name, None)
            self._states.pop(name, None)
        _g_breached.set(0.0, slo=name)

    def clear(self) -> None:
        with self._lock:
            names = list(self._objectives)
            self._objectives.clear()
            self._states.clear()
        for n in names:
            _g_breached.set(0.0, slo=n)

    def objectives(self) -> List[Objective]:
        with self._lock:
            return list(self._objectives.values())

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _burn(obj: Objective, points) -> Optional[float]:
        if not points:
            return None
        bad = sum(1 for _, v in points if obj.violates(v))
        return bad / len(points)

    def evaluate(self, store, now: Optional[float] = None) -> None:
        """One pass over every objective against ``store``
        (:class:`~tensorframes_tpu.obs.timeseries.TimeSeriesStore`);
        called by ``timeseries.sample_once`` after the tick's points
        land."""
        ts = time.time() if now is None else now
        for obj in self.objectives():
            with self._lock:
                st = self._states.get(obj.name)
            if st is None:
                continue
            fast = store.window(obj.series, obj.fast_window_s, now=ts)
            slow = store.window(obj.series, obj.slow_window_s, now=ts)
            if obj.ignore_zero:
                fast = [p for p in fast if p[1] != 0.0]
                slow = [p for p in slow if p[1] != 0.0]
            st.samples = len(fast)
            st.last_value = fast[-1][1] if fast else None
            fb = self._burn(obj, fast)
            sb = self._burn(obj, slow)
            st.fast_burn = 0.0 if fb is None else fb
            st.slow_burn = 0.0 if sb is None else sb
            breached = (
                fb is not None
                and len(fast) >= obj.min_samples
                and fb >= obj.burn_threshold
            )
            severity = None
            if breached:
                severity = (
                    "sustained"
                    if sb is not None and sb >= obj.burn_threshold
                    else "fast"
                )
            if breached and not st.breached:
                st.breached = True
                st.since = ts
                _m_breaches.inc(slo=obj.name)
                _g_breached.set(1.0, slo=obj.name)
                logger.warning(
                    "SLO %r breached (%s): %s %s %g, fast burn %.0f%% "
                    "over %gs",
                    obj.name, severity, obj.series,
                    ">" if obj.kind == "upper" else "<",
                    obj.bound, st.fast_burn * 100, obj.fast_window_s,
                )
                _flight.record(
                    "slo", "breach",
                    slo=obj.name, series=obj.series, bound=obj.bound,
                    bound_kind=obj.kind, severity=severity,
                    fast_burn=round(st.fast_burn, 4),
                    slow_burn=round(st.slow_burn, 4),
                    last_value=st.last_value,
                )
            elif st.breached and not breached:
                st.breached = False
                dur = ts - st.since if st.since is not None else None
                st.since = None
                _g_breached.set(0.0, slo=obj.name)
                logger.warning(
                    "SLO %r recovered (breached %.1fs)",
                    obj.name, dur or 0.0,
                )
                _flight.record(
                    "slo", "recovered",
                    slo=obj.name, series=obj.series,
                    breached_s=None if dur is None else round(dur, 3),
                )
            st.severity = severity

    # -- reporting ---------------------------------------------------------

    def status(self) -> List[Dict[str, Any]]:
        """One row per objective — the ``/statusz`` ``slo`` table and
        the ``/healthz`` ``slo`` payload."""
        out = []
        for obj in self.objectives():
            with self._lock:
                st = self._states.get(obj.name)
            if st is None:
                continue
            out.append({
                "name": obj.name,
                "series": obj.series,
                "bound": obj.bound,
                "kind": obj.kind,
                "breached": st.breached,
                "severity": st.severity,
                "since": st.since,
                "fast_burn": round(st.fast_burn, 4),
                "slow_burn": round(st.slow_burn, 4),
                "last_value": st.last_value,
                "window_samples": st.samples,
            })
        return out

    def degraded(self) -> bool:
        with self._lock:
            return any(s.breached for s in self._states.values())

    def reset(self) -> None:
        self.clear()


_monitor = SLOMonitor()


def monitor() -> SLOMonitor:
    """The process-wide default monitor (what ``/healthz`` degrades
    on)."""
    return _monitor


# -- canned objectives (the serving four) ------------------------------------


def ttft_p99(bound_s: float, **kw) -> Objective:
    """Time-to-first-token p99 must stay at or under ``bound_s``."""
    return Objective(
        name="ttft_p99", series="serve.ttft_seconds.p99",
        bound=float(bound_s), kind="upper", **kw,
    )


def tokens_per_s_floor(rate: float, **kw) -> Objective:
    """Aggregate emission rate must stay at or above ``rate`` tok/s —
    WHILE emitting: idle ticks (rate exactly 0) are excluded by default
    (``ignore_zero=True``), so a server with no demand is not
    "degraded"; pair with :func:`queue_depth_ceiling` to catch a server
    that has demand but is not serving it."""
    kw.setdefault("ignore_zero", True)
    return Objective(
        name="tokens_per_s", series="serve.tokens_total.rate",
        bound=float(rate), kind="lower", **kw,
    )


def error_rate_ceiling(rate: float, **kw) -> Objective:
    """Failed generation requests/second must stay at or under
    ``rate``."""
    return Objective(
        name="error_rate",
        series="serve.requests_total{status=failed}.rate",
        bound=float(rate), kind="upper", **kw,
    )


def queue_depth_ceiling(depth: float, **kw) -> Objective:
    """Admission-queue depth must stay at or under ``depth``."""
    return Objective(
        name="queue_depth", series="serve.queue_depth",
        bound=float(depth), kind="upper", **kw,
    )
