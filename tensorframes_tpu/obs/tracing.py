"""Distributed span tracing: trace ids, nested spans, JSONL event log.

``span(name, **attrs)`` is the one primitive. It nests via a thread-local
stack (each serving connection / decode worker gets its own tree), records
wall duration and — when a pytree is attached via the ``sync`` argument or
``Span.sync`` — a device-synchronized duration as well, and forwards to
``jax.profiler.TraceAnnotation`` so spans appear as named slices inside
Perfetto/TensorBoard traces captured by ``utils.profiling.trace()``.

**Identity** (the distributed layer, PR 10): every span carries a
``trace_id`` (32 lowercase hex chars — one END-TO-END request or job),
a ``span_id`` (16 hex chars, unique across processes), and a
``parent_id`` (the enclosing span, or the remote parent the trace was
adopted from). A :class:`TraceContext` names a position in a trace and
propagates it:

- **in-process, across threads** via a contextvar: a request thread
  wraps work in ``with use_trace(ctx): ...`` and every span opened on
  that thread (engine stepping, journal writers given the ctx) joins the
  trace;
- **across HTTP** via the W3C ``traceparent`` header
  (``00-<trace_id>-<span_id>-01``): ``interop/serving.py`` accepts it on
  ``POST /generate`` and echoes it back;
- **across processes** via the batch-job journal: ``engine/jobs.py``
  stamps the trace into ``manifest.json`` and every ledger record, so a
  distributed worker (``engine/dist_jobs.py``) continues the job's trace
  in another process — and the whole story is reconstructible
  post-mortem from ``ledger.jsonl`` plus the JSONL sink alone.

Completed spans are appended to a JSONL sink (one JSON object per line)
configured with :func:`set_trace_sink` or the ``TFT_TRACE_FILE``
environment variable. Event schema (stable; documented in
``docs/observability.md``)::

    {"name": str, "trace_id": "32hex", "span_id": "16hex",
     "parent_id": "16hex" | null, "depth": int,
     "ts": float epoch-seconds at entry, "dur_s": float wall,
     "dur_synced_s": float (only when a sync tree was attached),
     "thread": str, "attrs": {str: json-value}}

Events are written when a span CLOSES, so children appear before their
parents — consumers reconstruct the tree from ``parent_id`` and group
requests by ``trace_id``. :func:`event` emits a point event (``dur_s``
0, written immediately) — the record a crash cannot destroy, used by the
distributed-job lease claims so a kill -9'd worker's claim is still in
the trace.

A path-configured sink **rotates by size**: when the file would exceed
``max_bytes`` (default 64 MiB, ``TFT_TRACE_FILE_MAX_BYTES``), it is
renamed to ``<path>.1`` (replacing any previous ``.1``) and a fresh file
is started — the sink holds the last ~1–2 × ``max_bytes`` instead of
growing unbounded.

Everything honors the observability kill switch (``TFT_OBS=0`` /
``Config(observability=False)``): a disabled ``span()`` yields ``None``
and touches nothing.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Optional

from ..utils.logging import get_logger
from .metrics import enabled

__all__ = [
    "Span",
    "TraceContext",
    "current_span",
    "current_trace",
    "event",
    "new_trace",
    "set_annotations",
    "set_trace_sink",
    "span",
    "trace_sink",
    "use_trace",
]

logger = get_logger("obs.tracing")

_tls = threading.local()
_ids = itertools.count(1)
#: per-process id prefix: span ids must not collide across the worker
#: processes that share one trace (the distributed-jobs story), so each
#: process mints ids as <8 random hex><8 hex counter>
_PROC_PREFIX = os.urandom(4).hex()


def _new_span_id() -> str:
    return f"{_PROC_PREFIX}{next(_ids) & 0xFFFFFFFF:08x}"


def _new_trace_id() -> str:
    return os.urandom(16).hex()


class TraceContext:
    """A position inside one distributed trace: ``(trace_id, span_id)``.
    ``span_id`` is the id new child spans parent to — the W3C
    ``parent-id``. Immutable and tiny; safe to hand across threads and
    serialize into headers, manifests, and ledger records."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, TraceContext)
            and other.trace_id == self.trace_id
            and other.span_id == self.span_id
        )

    def __hash__(self) -> int:
        return hash((self.trace_id, self.span_id))

    def child(self) -> "TraceContext":
        """Same trace, fresh span id (a synthetic intermediate node)."""
        return TraceContext(self.trace_id, _new_span_id())

    # -- W3C traceparent ---------------------------------------------------

    def traceparent(self) -> str:
        """This position as a W3C ``traceparent`` header value
        (version 00, sampled flag set)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header: Optional[str]) -> Optional["TraceContext"]:
        """Parse a ``traceparent`` header; ``None`` for a missing or
        malformed value (a bad header must never fail the request —
        tracing degrades to a fresh trace instead)."""
        if not header:
            return None
        parts = header.strip().lower().split("-")
        if len(parts) < 4:
            return None
        version, trace_id, span_id = parts[0], parts[1], parts[2]
        if (
            len(version) != 2
            or len(trace_id) != 32
            or len(span_id) != 16
            or version == "ff"
            or trace_id == "0" * 32
            or span_id == "0" * 16
        ):
            return None
        try:
            int(trace_id, 16), int(span_id, 16)
        except ValueError:
            return None
        return cls(trace_id, span_id)


def new_trace() -> TraceContext:
    """A fresh root trace context (new trace_id, synthetic root span id).
    Spans opened under ``use_trace(new_trace())`` parent to the synthetic
    root — the same shape as adopting a remote parent."""
    return TraceContext(_new_trace_id(), _new_span_id())


#: the ambient trace position for code with no open span on its thread —
#: how a request's identity crosses into worker threads (the engine's
#: stepping loop, journal writers). A contextvar rather than a
#: thread-local so async frameworks layered on top inherit it naturally.
_ctx_var: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("tft_trace_ctx", default=None)
)


class use_trace:
    """Install ``ctx`` as the ambient trace for the block::

        with use_trace(ctx):
            ...           # spans opened here join ctx's trace

    ``None`` is a no-op (propagating an absent trace must cost nothing
    and not mask an outer one)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._token = None

    def __enter__(self) -> Optional[TraceContext]:
        if self._ctx is not None:
            self._token = _ctx_var.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            _ctx_var.reset(self._token)
        return False


def current_trace() -> Optional[TraceContext]:
    """The calling thread's trace position: the innermost OPEN span if
    one exists, else the ambient :class:`use_trace` context, else
    ``None``. This is what crosses boundaries — stamp it into a header /
    manifest / submit call on one side, ``use_trace`` it on the other."""
    stack = getattr(_tls, "stack", None)
    if stack:
        top = stack[-1]
        return TraceContext(top.trace_id, top.span_id)
    return _ctx_var.get()


_sink_lock = threading.Lock()
_sink = None
_sink_owned = False  # we opened it (path arg) and must close it

#: rotation default for path sinks: ~64 MiB, env-overridable
_DEFAULT_MAX_BYTES = 64 << 20


def _env_max_bytes() -> int:
    try:
        return int(
            os.environ.get("TFT_TRACE_FILE_MAX_BYTES", _DEFAULT_MAX_BYTES)
        )
    except ValueError:
        return _DEFAULT_MAX_BYTES


class _RotatingFile:
    """Append sink with size-based rotation: when a write would push the
    file past ``max_bytes``, the current file is renamed to ``<path>.1``
    (dropping the previous ``.1``) and a fresh file begins — the JSONL
    sink keeps the last ~``max_bytes``..2×``max_bytes`` of spans instead
    of growing without bound (``TFT_TRACE_FILE`` used to). Rotation is
    line-atomic: events are whole lines and a rotation happens only
    between writes. ``max_bytes <= 0`` disables rotation."""

    def __init__(self, path: str, max_bytes: int):
        self.path = os.fspath(path)
        self.max_bytes = int(max_bytes)
        self._f = open(self.path, "a", buffering=1)
        try:
            self._size = os.path.getsize(self.path)
        except OSError:
            self._size = 0

    def write(self, data: str) -> int:
        if self.max_bytes > 0:
            # multiple PROCESSES may share one TFT_TRACE_FILE (the
            # distributed-jobs workers do): if another process rotated
            # the path out from under us, our O_APPEND fd now follows
            # the renamed .1 inode — re-attach to the live path instead
            # of writing into (and later clobbering) the archive. The
            # same stat's st_size is the authoritative file size (a
            # process-local byte counter misses the siblings' appends
            # and would let the shared file grow to K x max_bytes).
            try:
                st = os.stat(self.path)
                if st.st_ino != os.fstat(self._f.fileno()).st_ino:
                    self._reopen()
                else:
                    self._size = st.st_size
            except OSError:
                self._reopen()
            if self._size and self._size + len(data) > self.max_bytes:
                self._rotate()
        n = self._f.write(data)
        self._size += len(data)
        return n

    def _rotate(self) -> None:
        try:
            # last-instant re-check: a sibling PROCESS may have rotated
            # between our size check and here — renaming our stale view
            # over its fresh archive would destroy up to max_bytes of
            # just-preserved spans; re-attach instead
            if (
                os.stat(self.path).st_ino
                != os.fstat(self._f.fileno()).st_ino
            ):
                self._reopen()
                return
            self._f.close()
            os.replace(self.path, self.path + ".1")
        except OSError:
            logger.warning("trace sink rotation failed", exc_info=True)
        self._reopen()

    def _reopen(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
        self._f = open(self.path, "a", buffering=1)
        try:
            self._size = os.path.getsize(self.path)
        except OSError:
            self._size = 0

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def set_trace_sink(sink, max_bytes: Optional[int] = None) -> None:
    """Route span events: a path (opened append, line-buffered, with
    size rotation — ``max_bytes`` defaults to ~64 MiB or
    ``TFT_TRACE_FILE_MAX_BYTES``; ``<= 0`` disables rotation), a
    file-like object (used as-is, not closed, never rotated), or
    ``None`` to disable. Replacing a path-opened sink closes it."""
    global _sink, _sink_owned
    with _sink_lock:
        if _sink_owned and _sink is not None:
            try:
                _sink.close()
            except OSError:
                pass
        if sink is None:
            _sink, _sink_owned = None, False
        elif isinstance(sink, (str, os.PathLike)):
            limit = _env_max_bytes() if max_bytes is None else int(max_bytes)
            _sink, _sink_owned = _RotatingFile(sink, limit), True
        else:
            _sink, _sink_owned = sink, False


def trace_sink():
    """The active sink file object (``None`` when disabled)."""
    return _sink


class Span:
    """One live span (its own context manager — the generator-based
    ``contextlib`` route costs ~2 µs per use, real money at engine-dispatch
    frequency). Mutate ``attrs`` (or assign ``sync``) inside the ``with``
    block to enrich the event before it is emitted."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "depth", "attrs",
        "sync", "ts", "_t0", "_ann",
    )

    def __init__(self, name, sync, attrs):
        self.name = name
        self.trace_id: Optional[str] = None  # resolved at __enter__
        self.span_id = _new_span_id()
        self.parent_id: Optional[str] = None
        self.depth = 0
        self.attrs: Dict[str, Any] = attrs
        self.sync = sync
        self.ts = 0.0
        self._t0 = 0.0
        self._ann = None

    def __enter__(self) -> "Span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        if stack:
            parent = stack[-1]
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
            self.depth = len(stack)
        else:
            ctx = _ctx_var.get()
            if ctx is not None:
                self.trace_id = ctx.trace_id
                self.parent_id = ctx.span_id
            else:
                self.trace_id = _new_trace_id()
        stack.append(self)
        if _annotations_on:
            ann_cls = _annotation_cls()
            if ann_cls is not None:
                self._ann = ann_cls(self.name)
                self._ann.__enter__()
        self.ts = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        wall = time.perf_counter() - self._t0
        synced = None
        if self.sync is not None:
            try:
                import jax

                jax.block_until_ready(self.sync)
                synced = time.perf_counter() - self._t0
            except Exception:
                pass  # sync is best-effort diagnostics, never a failure
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        _emit(self, wall, synced)
        return False


def current_span() -> Optional[Span]:
    """The innermost open span on this thread, or ``None``."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


#: mirror of ``flight.capture_spans``'s state, kept as a plain module
#: global here so the disabled ``span()`` fast path stays one predicate
#: (``obs/flight.py`` flips it via :func:`_set_flight_capture`)
_flight_spans_on = False


def _set_flight_capture(on: bool) -> None:
    global _flight_spans_on
    _flight_spans_on = bool(on)


def _emit(s: Span, wall: float, synced: Optional[float]) -> None:
    if _flight_spans_on:
        from . import flight as _flight

        _flight.record_span(s.name, s.trace_id, s.span_id, wall, s.attrs)
    if _sink is None:
        return
    event = {
        "name": s.name,
        "trace_id": s.trace_id,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "depth": s.depth,
        "ts": s.ts,
        "dur_s": wall,
        "thread": threading.current_thread().name,
        "attrs": s.attrs,
    }
    if synced is not None:
        event["dur_synced_s"] = synced
    _write_event(event)


def _write_event(event: Dict[str, Any]) -> None:
    try:
        line = json.dumps(event, default=str) + "\n"
    except (TypeError, ValueError):  # pathological attrs must not raise
        event["attrs"] = {k: str(v) for k, v in event["attrs"].items()}
        line = json.dumps(event, default=str) + "\n"
    with _sink_lock:
        sink = _sink
        if sink is None:
            return
        try:
            sink.write(line)
        except (OSError, ValueError):
            logger.warning("span sink write failed; disabling sink")
            globals()["_sink"] = None
            globals()["_sink_owned"] = False


def event(name: str, **attrs) -> Optional[TraceContext]:
    """Emit a POINT event into the current trace: a zero-duration span
    record written to the sink immediately (and mirrored into the flight
    recorder). This is the record a crash cannot destroy — the
    distributed-job lease claim uses it so a worker kill -9'd mid-block
    still left its claim in the trace. Returns the event's own
    :class:`TraceContext` (for chaining), or ``None`` when disabled."""
    if not enabled():
        return None
    ctx = current_trace()
    sid = _new_span_id()
    trace_id = ctx.trace_id if ctx is not None else _new_trace_id()
    parent_id = ctx.span_id if ctx is not None else None
    if _flight_spans_on:
        from . import flight as _flight

        _flight.record_span(name, trace_id, sid, 0.0, attrs)
    if _sink is not None:
        _write_event(
            {
                "name": name,
                "trace_id": trace_id,
                "span_id": sid,
                "parent_id": parent_id,
                "depth": 0,
                "ts": time.time(),
                "dur_s": 0.0,
                "thread": threading.current_thread().name,
                "attrs": attrs,
                "kind": "event",
            }
        )
    return TraceContext(trace_id, sid)


_ann_cls = None
_ann_tried = False
#: forward spans to jax.profiler.TraceAnnotation only while someone is
#: actually capturing a trace: an annotation inside a dispatching pass
#: measures ~5-10 µs (TraceMe + pybind crossing on a cold cache), which is
#: pure waste when no Perfetto session exists to receive it.
#: ``utils.profiling.trace()`` flips this automatically; direct
#: ``jax.profiler.start_trace`` users call :func:`set_annotations`.
_annotations_on = False


def set_annotations(on: bool) -> None:
    """Enable/disable TraceAnnotation forwarding for spans (normally
    managed by ``utils.profiling.trace()``)."""
    global _annotations_on
    _annotations_on = bool(on)


def _annotation_cls():
    """``jax.profiler.TraceAnnotation`` resolved once (or ``None`` when
    jax/its profiler is unavailable — spans must work without it)."""
    global _ann_cls, _ann_tried
    if not _ann_tried:
        _ann_tried = True
        try:
            import jax

            _ann_cls = jax.profiler.TraceAnnotation
        except Exception:
            _ann_cls = None
    return _ann_cls


class _NullSpan:
    """Context manager for the disabled state: ``as`` binds ``None``."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL = _NullSpan()


def span(name: str, sync=None, **attrs):
    """Open a nested span::

        with span("engine.map_blocks", partitions=4) as sp:
            out = run()
            sp.sync = out          # optional: device-synced duration
            sp.attrs["rows"] = n   # optional: enrich the event

    Binds the :class:`Span` (or ``None`` when observability is off).
    ``sync`` / ``Span.sync`` holds a pytree passed to
    ``jax.block_until_ready`` before the synced duration is taken — only
    attach work the caller is about to materialize anyway; syncing a
    deliberately device-resident result would serialize the pipeline.

    Spans are event producers: with no JSONL sink configured, no
    profiler trace listening, and flight-recorder span capture off, a
    span has no observable effect, so the whole mechanism is skipped
    (engine dispatch loops then pay one predicate per op instead of
    allocation + clock reads). Consumers attach by setting a sink /
    opening ``utils.profiling.trace()`` / enabling
    ``flight.capture_spans`` BEFORE the work they want to see.
    """
    if not enabled() or (
        _sink is None and not _annotations_on and not _flight_spans_on
    ):
        return _NULL
    return Span(name, sync, dict(attrs))


if os.environ.get("TFT_TRACE_FILE"):
    try:
        set_trace_sink(os.environ["TFT_TRACE_FILE"])
    except OSError:
        logger.warning(
            "TFT_TRACE_FILE=%r could not be opened; span sink disabled",
            os.environ["TFT_TRACE_FILE"],
        )
