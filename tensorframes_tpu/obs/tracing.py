"""Span tracing: nested wall-clock spans, JSONL event log, Perfetto hookup.

``span(name, **attrs)`` is the one primitive. It nests via a thread-local
stack (each serving connection / decode worker gets its own tree), records
wall duration and — when a pytree is attached via the ``sync`` argument or
``Span.sync`` — a device-synchronized duration as well, and forwards to
``jax.profiler.TraceAnnotation`` so spans appear as named slices inside
Perfetto/TensorBoard traces captured by ``utils.profiling.trace()``.

Completed spans are appended to a JSONL sink (one JSON object per line)
configured with :func:`set_trace_sink` or the ``TFT_TRACE_FILE``
environment variable. Event schema (stable; documented in
``docs/observability.md``)::

    {"name": str, "span_id": int, "parent_id": int | null, "depth": int,
     "ts": float epoch-seconds at entry, "dur_s": float wall,
     "dur_synced_s": float (only when a sync tree was attached),
     "thread": str, "attrs": {str: json-value}}

Events are written when a span CLOSES, so children appear before their
parents — consumers reconstruct the tree from ``parent_id``.

Everything honors the observability kill switch (``TFT_OBS=0`` /
``Config(observability=False)``): a disabled ``span()`` yields ``None``
and touches nothing.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Optional

from ..utils.logging import get_logger
from .metrics import enabled

__all__ = [
    "Span",
    "span",
    "current_span",
    "set_trace_sink",
    "trace_sink",
    "set_annotations",
]

logger = get_logger("obs.tracing")

_tls = threading.local()
_ids = itertools.count(1)

_sink_lock = threading.Lock()
_sink = None
_sink_owned = False  # we opened it (path arg) and must close it


class Span:
    """One live span (its own context manager — the generator-based
    ``contextlib`` route costs ~2 µs per use, real money at engine-dispatch
    frequency). Mutate ``attrs`` (or assign ``sync``) inside the ``with``
    block to enrich the event before it is emitted."""

    __slots__ = (
        "name", "span_id", "parent_id", "depth", "attrs", "sync", "ts",
        "_t0", "_ann",
    )

    def __init__(self, name, sync, attrs):
        self.name = name
        self.span_id = next(_ids)
        self.parent_id = None
        self.depth = 0
        self.attrs: Dict[str, Any] = attrs
        self.sync = sync
        self.ts = 0.0
        self._t0 = 0.0
        self._ann = None

    def __enter__(self) -> "Span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        if stack:
            parent = stack[-1]
            self.parent_id = parent.span_id
            self.depth = len(stack)
        stack.append(self)
        if _annotations_on:
            ann_cls = _annotation_cls()
            if ann_cls is not None:
                self._ann = ann_cls(self.name)
                self._ann.__enter__()
        self.ts = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        wall = time.perf_counter() - self._t0
        synced = None
        if self.sync is not None:
            try:
                import jax

                jax.block_until_ready(self.sync)
                synced = time.perf_counter() - self._t0
            except Exception:
                pass  # sync is best-effort diagnostics, never a failure
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        _emit(self, wall, synced)
        return False


def current_span() -> Optional[Span]:
    """The innermost open span on this thread, or ``None``."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def set_trace_sink(sink) -> None:
    """Route span events: a path (opened append, line-buffered), a
    file-like object (used as-is, not closed), or ``None`` to disable.
    Replacing a path-opened sink closes it."""
    global _sink, _sink_owned
    with _sink_lock:
        if _sink_owned and _sink is not None:
            try:
                _sink.close()
            except OSError:
                pass
        if sink is None:
            _sink, _sink_owned = None, False
        elif isinstance(sink, (str, os.PathLike)):
            _sink, _sink_owned = open(sink, "a", buffering=1), True
        else:
            _sink, _sink_owned = sink, False


def trace_sink():
    """The active sink file object (``None`` when disabled)."""
    return _sink


def _emit(s: Span, wall: float, synced: Optional[float]) -> None:
    if _sink is None:
        return
    event = {
        "name": s.name,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "depth": s.depth,
        "ts": s.ts,
        "dur_s": wall,
        "thread": threading.current_thread().name,
        "attrs": s.attrs,
    }
    if synced is not None:
        event["dur_synced_s"] = synced
    try:
        line = json.dumps(event, default=str) + "\n"
    except (TypeError, ValueError):  # pathological attrs must not raise
        event["attrs"] = {k: str(v) for k, v in s.attrs.items()}
        line = json.dumps(event, default=str) + "\n"
    with _sink_lock:
        sink = _sink
        if sink is None:
            return
        try:
            sink.write(line)
        except (OSError, ValueError):
            logger.warning("span sink write failed; disabling sink")
            globals()["_sink"] = None
            globals()["_sink_owned"] = False


_ann_cls = None
_ann_tried = False
#: forward spans to jax.profiler.TraceAnnotation only while someone is
#: actually capturing a trace: an annotation inside a dispatching pass
#: measures ~5-10 µs (TraceMe + pybind crossing on a cold cache), which is
#: pure waste when no Perfetto session exists to receive it.
#: ``utils.profiling.trace()`` flips this automatically; direct
#: ``jax.profiler.start_trace`` users call :func:`set_annotations`.
_annotations_on = False


def set_annotations(on: bool) -> None:
    """Enable/disable TraceAnnotation forwarding for spans (normally
    managed by ``utils.profiling.trace()``)."""
    global _annotations_on
    _annotations_on = bool(on)


def _annotation_cls():
    """``jax.profiler.TraceAnnotation`` resolved once (or ``None`` when
    jax/its profiler is unavailable — spans must work without it)."""
    global _ann_cls, _ann_tried
    if not _ann_tried:
        _ann_tried = True
        try:
            import jax

            _ann_cls = jax.profiler.TraceAnnotation
        except Exception:
            _ann_cls = None
    return _ann_cls


class _NullSpan:
    """Context manager for the disabled state: ``as`` binds ``None``."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL = _NullSpan()


def span(name: str, sync=None, **attrs):
    """Open a nested span::

        with span("engine.map_blocks", partitions=4) as sp:
            out = run()
            sp.sync = out          # optional: device-synced duration
            sp.attrs["rows"] = n   # optional: enrich the event

    Binds the :class:`Span` (or ``None`` when observability is off).
    ``sync`` / ``Span.sync`` holds a pytree passed to
    ``jax.block_until_ready`` before the synced duration is taken — only
    attach work the caller is about to materialize anyway; syncing a
    deliberately device-resident result would serialize the pipeline.

    Spans are event producers: with no JSONL sink configured and no
    profiler trace listening, a span has no observable effect, so the
    whole mechanism is skipped (engine dispatch loops then pay one
    predicate per op instead of allocation + clock reads). Consumers
    attach by setting a sink / opening ``utils.profiling.trace()``
    BEFORE the work they want to see.
    """
    if not enabled() or (_sink is None and not _annotations_on):
        return _NULL
    return Span(name, sync, dict(attrs))


if os.environ.get("TFT_TRACE_FILE"):
    try:
        set_trace_sink(os.environ["TFT_TRACE_FILE"])
    except OSError:
        logger.warning(
            "TFT_TRACE_FILE=%r could not be opened; span sink disabled",
            os.environ["TFT_TRACE_FILE"],
        )
