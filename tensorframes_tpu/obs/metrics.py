"""Thread-safe metrics registry: counters, gauges, log-bucket histograms.

The reference delegated all runtime visibility to Spark's UI and task
metrics (SURVEY §5: timing was "manual prints in ignored suites"); this
registry is the replacement signal path for an engine that has no Spark
around it. Design constraints, in order:

1. **hot-path cheap** — instrumentation sits inside the engine's dispatch
   loops and the serving accept path, so a disabled registry must cost one
   predicate and an enabled increment one lock + dict update. Metrics are
   created once at module import and held in module globals by their
   instrumenting module (no name lookup per increment).
2. **thread-safe** — the scoring server increments from its connection
   pool, the engine from decode/prefetch threads; every series mutation
   happens under its metric's lock.
3. **two export shapes** — ``snapshot()`` returns a plain dict (JSON-able,
   for tests/logging/BENCH files), ``render_prometheus()`` returns
   exposition text (scraped off the serving port, see
   ``interop/serving.py``).

Metric names are dotted (``engine.rows_processed_total``); Prometheus
rendering prefixes ``tft_`` and maps dots to underscores
(``tft_engine_rows_processed_total``).

Kill switch: ``TFT_OBS=0`` in the environment (read once at import) or
``Config(observability=False)`` disables all collection — increments,
histogram observations, and span emission become no-ops.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.config import get_config, register_on_change

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "enabled",
    "registry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "render_prometheus",
    "quantile_from_counts",
]

#: environment kill switch, read once — flipping the env var mid-process is
#: not a supported path (use ``set_config(observability=...)`` for that)
_ENV_OFF = os.environ.get("TFT_OBS", "1").strip().lower() in (
    "0", "false", "off", "no",
)

#: the hot-path gate: a plain module global (one dict lookup to read),
#: kept in sync with Config.observability by a set_config callback —
#: deriving it per increment costs two extra function calls on every
#: counter touch in the engine dispatch loop
_ON = False


def _refresh_enabled() -> None:
    global _ON
    _ON = (not _ENV_OFF) and get_config().observability


register_on_change(_refresh_enabled)


def enabled() -> bool:
    """Whether collection is on (``TFT_OBS`` env AND the Config field)."""
    return _ON


#: default histogram bounds: log-scale, factor 4, 1 µs .. ~67 s — wide
#: enough for both sub-ms device dispatches and multi-second cold compiles,
#: fixed so series from different processes always merge bucket-for-bucket
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(1e-6 * 4.0 ** i for i in range(14))


def _check_labels(
    declared: Tuple[str, ...], got: Dict[str, Any], name: str
) -> Tuple[str, ...]:
    """Label dict -> series key, enforcing the declared label set (a typo'd
    label name must fail loudly, not create a parallel series)."""
    if len(got) != len(declared):
        raise ValueError(
            f"metric {name!r} declares labels {declared}; got "
            f"{tuple(sorted(got))}"
        )
    try:
        return tuple(str(got[k]) for k in declared)
    except KeyError as e:
        raise ValueError(
            f"metric {name!r} declares labels {declared}; got "
            f"{tuple(sorted(got))}"
        ) from e


class _Metric:
    """Shared shell: name, help text, declared label names, series lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names: Tuple[str, ...] = tuple(labels)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if not labels and not self.label_names:
            return ()
        return _check_labels(self.label_names, labels, self.name)


class BoundCounter:
    """A counter pre-bound to one label combination: the per-increment
    label-dict validation and key construction are paid once at bind time,
    which matters for fixed-label series on the engine dispatch path."""

    __slots__ = ("_counter", "_key")

    def __init__(self, counter: "Counter", key: Tuple[str, ...]):
        self._counter = counter
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        if not _ON:
            return
        if amount < 0:
            raise ValueError(
                f"counter {self._counter.name!r} cannot decrease"
            )
        c = self._counter
        with c._lock:
            c._values[self._key] = c._values.get(self._key, 0.0) + amount


class Counter(_Metric):
    """Monotonic counter; ``inc(amount, **labels)``. Hot paths with a fixed
    label combination should ``bind(**labels)`` once and increment the
    bound handle."""

    kind = "counter"

    def __init__(self, name, help="", labels=()):
        super().__init__(name, help, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not _ON:
            return
        if amount < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def bind(self, **labels) -> BoundCounter:
        return BoundCounter(self, self._key(labels))

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def _series(self):
        with self._lock:
            return dict(self._values)

    def _reset(self):
        with self._lock:
            self._values.clear()


class Gauge(_Metric):
    """Point-in-time value; ``set``/``inc``/``dec``."""

    kind = "gauge"

    def __init__(self, name, help="", labels=()):
        super().__init__(name, help, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        if not _ON:
            return
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not _ON:
            return
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def adjust(self, amount: float, **labels) -> None:
        """Unconditional add, bypassing the kill switch — for PAIRED
        lifecycle updates (inc at start, dec in a finally) that must stay
        balanced even when ``set_config(observability=...)`` flips mid
        flight; a gated dec would otherwise no-op and leave the gauge
        drifted forever. Callers gate the PAIR on one snapshot of
        ``enabled()`` instead."""
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def _series(self):
        with self._lock:
            return dict(self._values)

    def _reset(self):
        with self._lock:
            self._values.clear()


class Histogram(_Metric):
    """Fixed log-scale-bucket histogram; ``observe(value, **labels)``.

    Bucket bounds are upper-inclusive (Prometheus ``le`` semantics): an
    observation exactly on a bound lands in that bound's bucket. Values
    above the last bound land in the implicit ``+Inf`` bucket.
    """

    kind = "histogram"

    def __init__(self, name, help="", labels=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labels)
        bounds = tuple(float(b) for b in buckets)
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(f"histogram {name!r} buckets must be increasing")
        self.bounds: Tuple[float, ...] = bounds
        #: key -> [per-bucket counts (+Inf last), sum, count]
        self._values: Dict[Tuple[str, ...], List[Any]] = {}

    def observe(self, value: float, **labels) -> None:
        if not _ON:
            return
        key = self._key(labels)
        # le-inclusive: bisect_left puts v == bound into bound's bucket
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            series = self._values.get(key)
            if series is None:
                series = self._values[key] = [
                    [0] * (len(self.bounds) + 1), 0.0, 0,
                ]
            series[0][idx] += 1
            series[1] += value
            series[2] += 1

    def series(self, **labels) -> Optional[Dict[str, Any]]:
        """One series as ``{"counts": [...], "sum": s, "count": n}`` —
        counts are per-bucket (NON-cumulative), ``+Inf`` last."""
        s = self._values.get(self._key(labels))
        if s is None:
            return None
        with self._lock:
            return {"counts": list(s[0]), "sum": s[1], "count": s[2]}

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Approximate ``q``-quantile from the fixed buckets: the
        smallest upper bound whose cumulative count reaches
        ``q * count`` (observations in the ``+Inf`` tail report the top
        bound — an UNDERestimate there, which is the conservative
        direction for the latency-derived hints this feeds). ``None``
        when the series has no samples. Consumers: the serving 503
        ``Retry-After`` estimate (``interop/serving.py``) and the
        time-series sampler's per-tick p50/p99 (``obs/timeseries.py``)."""
        s = self.series(**labels)
        if s is None:
            if not 0.0 <= q <= 1.0:  # argument errors never go silent
                raise ValueError(f"quantile q must be in [0, 1]; got {q}")
            return None
        return quantile_from_counts(self.bounds, s["counts"], s["count"], q)

    def _series(self):
        with self._lock:
            return {
                k: {"counts": list(v[0]), "sum": v[1], "count": v[2]}
                for k, v in self._values.items()
            }

    def _reset(self):
        with self._lock:
            self._values.clear()


def quantile_from_counts(
    bounds: Sequence[float],
    counts: Sequence[int],
    count: int,
    q: float,
) -> Optional[float]:
    """The bucket-quantile rule shared by :meth:`Histogram.quantile` and
    the time-series sampler (which works from ``_series()`` snapshots,
    not live metrics): the smallest upper bound whose cumulative count
    reaches ``max(q * count, 1)`` observations — the ``max(..., 1)``
    keeps ``q = 0`` (and tiny ``q`` on small series) at the smallest
    bucket that actually HOLDS an observation instead of the registry's
    first bound, which may never have been observed. A series entirely
    in the ``+Inf`` tail reports the top finite bound (a documented
    underestimate — the conservative direction for latency hints).
    ``None`` for an empty series."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile q must be in [0, 1]; got {q}")
    if not count:
        return None
    target = max(q * count, 1)
    cum = 0
    for bound, cnt in zip(bounds, counts):
        cum += cnt
        if cum >= target:
            return bound
    return bounds[-1]


def _label_str(names: Tuple[str, ...], key: Tuple[str, ...]) -> str:
    return ",".join(f"{n}={v}" for n, v in zip(names, key))


def _prom_name(name: str) -> str:
    return "tft_" + name.replace(".", "_").replace("-", "_")


def _prom_escape(v: str) -> str:
    """Label-VALUE escaping per the exposition format (0.0.4): backslash
    first (so the escapes it introduces survive), then double-quote,
    then newline. A label value carrying exception text — the `status`
    reasons on failure counters do — must round-trip a scrape parse."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_escape_help(v: str) -> str:
    """HELP-text escaping: the exposition format escapes backslash and
    newline there (quotes stay literal — HELP text is not quoted). Help
    strings are author-controlled, but one embedded newline would split
    the line and corrupt every series after it in the scrape."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_labels(names: Tuple[str, ...], key: Tuple[str, ...], extra="") -> str:
    parts = [f'{n}="{_prom_escape(v)}"' for n, v in zip(names, key)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    f = float(v)
    if math.isinf(f):
        # Prometheus exposition spelling; int(inf) raises, and one bad
        # observation must never 500 the whole scrape
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    return str(int(f)) if f == int(f) else repr(f)


class MetricsRegistry:
    """Get-or-create metric registry. Creation is idempotent: asking for an
    existing name returns the existing metric (type and label mismatches
    raise — two modules silently disagreeing about a metric is a bug)."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labels, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.label_names}; requested "
                        f"{cls.kind}{tuple(labels)}"
                    )
                return m
            m = cls(name, help, labels, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labels=()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(
        self, name, help="", labels=(), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets
        )

    def get(self, name: str) -> _Metric:
        return self._metrics[name]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Zero every series (registrations survive) — test isolation."""
        for m in list(self._metrics.values()):
            m._reset()

    def snapshot(self) -> Dict[str, Any]:
        """Plain (JSON-serializable) dict of everything collected."""
        out: Dict[str, Any] = {}
        for name in self.names():
            m = self._metrics[name]
            out[name] = {
                "type": m.kind,
                "help": m.help,
                "labels": list(m.label_names),
                "values": {
                    _label_str(m.label_names, k): v
                    for k, v in m._series().items()
                },
            }
            if isinstance(m, Histogram):
                out[name]["buckets"] = list(m.bounds)
        return out

    def render_prometheus(self) -> str:
        """Prometheus exposition text (format 0.0.4)."""
        lines: List[str] = []
        for name in self.names():
            m = self._metrics[name]
            pname = _prom_name(name)
            if m.help:
                lines.append(f"# HELP {pname} {_prom_escape_help(m.help)}")
            lines.append(f"# TYPE {pname} {m.kind}")
            series = m._series()
            if isinstance(m, Histogram):
                for key, s in sorted(series.items()):
                    cum = 0
                    for bound, cnt in zip(m.bounds, s["counts"]):
                        cum += cnt
                        lab = _prom_labels(
                            m.label_names, key, extra=f'le="{bound!r}"'
                        )
                        lines.append(f"{pname}_bucket{lab} {cum}")
                    cum += s["counts"][-1]
                    lab = _prom_labels(m.label_names, key, extra='le="+Inf"')
                    lines.append(f"{pname}_bucket{lab} {cum}")
                    lab = _prom_labels(m.label_names, key)
                    lines.append(f"{pname}_sum{lab} {_fmt(s['sum'])}")
                    lines.append(f"{pname}_count{lab} {s['count']}")
            else:
                for key, v in sorted(series.items()):
                    lab = _prom_labels(m.label_names, key)
                    lines.append(f"{pname}{lab} {_fmt(v)}")
        return "\n".join(lines) + "\n"


_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry (what the serving scrape exports)."""
    return _default


def counter(name, help="", labels=()) -> Counter:
    return _default.counter(name, help, labels)


def gauge(name, help="", labels=()) -> Gauge:
    return _default.gauge(name, help, labels)


def histogram(name, help="", labels=(), buckets=DEFAULT_BUCKETS) -> Histogram:
    return _default.histogram(name, help, labels, buckets)


def snapshot() -> Dict[str, Any]:
    return _default.snapshot()


def render_prometheus() -> str:
    return _default.render_prometheus()
