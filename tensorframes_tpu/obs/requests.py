"""Per-request cost attribution: what each served request actually cost.

Aggregate serving metrics (tokens/s, queue depth) say how the ENGINE is
doing; multi-tenant QoS (ROADMAP item 2) needs to know what each
REQUEST did — only attributable costs can be quota'd, shed, or billed.
The serving engine calls :func:`record_request` from its scheduler's
finish hook with the request's terminal accounting:

- ``tokens`` generated, ``kv_pages`` held at finish, and
  ``prefix_cached_tokens`` the prefix cache saved it from prefilling;
- ``est_flops``: the estimated floating-point cost — each prefill /
  chunk / decode / draft / verify dispatch's ``ProgramRecord`` FLOP
  estimate, apportioned equally over the requests sharing the batch
  that dispatch served;
- speculative proposal/acceptance counts (the per-request acceptance
  rate);
- the ``tenant`` label (defaulting to the fleet session id) — the key
  QoS policies will act on.

Records land in a bounded in-memory ring (the ``/statusz``
``request_costs`` top-N reads it) and append to a bounded
``requests.jsonl`` (rotated once over the size cap, same policy as the
trace sink) next to the job journals — the durable feed for offline
cost analysis and the learned cost model's per-request training data.
Kill-switch parity: under ``TFT_OBS=0`` nothing is recorded or written.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import get_logger
from .metrics import enabled

__all__ = [
    "record_request",
    "recent",
    "requests_path",
    "reset",
    "top_by_cost",
]

logger = get_logger("obs.requests")

#: in-memory ring depth — enough for a top-N over recent traffic
#: without ever growing with uptime
_RING = 512

#: rotate requests.jsonl past this size (the current file moves to
#: ``.1``, replacing any previous ``.1`` — at most 2x the cap on disk)
_MAX_BYTES = 8 << 20

_lock = threading.Lock()
_records: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=_RING
)
_write_failed = False  # warn once; cost accounting must not spam


def requests_path() -> str:
    """Where request records persist: ``TFT_REQUESTS_FILE``, else
    ``requests.jsonl`` next to the batch-job journal root (the same
    trajectory directory ``programs.jsonl`` and the bench artifacts
    use)."""
    explicit = os.environ.get("TFT_REQUESTS_FILE", "")
    if explicit:
        return explicit
    from ..utils.config import get_config

    root = (
        get_config().job_dir
        or os.environ.get("TFT_JOB_DIR")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "tensorframes_tpu", "jobs"
        )
    )
    return os.path.join(root, "requests.jsonl")


def record_request(**fields: Any) -> Optional[Dict[str, Any]]:
    """Record one finished request's cost row; returns the row (or
    ``None`` under the kill switch). Never raises — accounting sits on
    the engine's finish path."""
    if not enabled():
        return None
    row = {"ts": round(time.time(), 3)}
    row.update(fields)
    with _lock:
        _records.append(row)
    _append_line(row)
    return row


def _append_line(row: Dict[str, Any]) -> None:
    global _write_failed
    try:
        path = requests_path()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with _lock:
            try:
                if os.path.getsize(path) >= _MAX_BYTES:
                    os.replace(path, path + ".1")
            except OSError:
                pass  # absent file: nothing to rotate
            with open(path, "a") as f:
                f.write(json.dumps(row, default=str) + "\n")
    except Exception:
        if not _write_failed:
            _write_failed = True
            logger.warning(
                "request-cost persist failed (suppressing further "
                "warnings)", exc_info=True,
            )


def recent(n: int = _RING) -> List[Dict[str, Any]]:
    """Newest-last copy of the in-memory ring (at most ``n`` rows)."""
    with _lock:
        rows = list(_records)
    return rows[-n:]


def top_by_cost(n: int = 10) -> List[Dict[str, Any]]:
    """The ``n`` most expensive recent requests by ``est_flops``
    (tokens break ties — a request served entirely from cache hints
    has no FLOP estimate but still did work) — the ``/statusz``
    ``request_costs`` table."""
    with _lock:
        rows = list(_records)
    rows.sort(
        key=lambda r: (
            float(r.get("est_flops") or 0.0),
            int(r.get("tokens") or 0),
        ),
        reverse=True,
    )
    return rows[:n]


def reset() -> None:
    """Drop the in-memory ring (the JSONL is untouched) — test
    isolation."""
    global _write_failed
    with _lock:
        _records.clear()
    _write_failed = False
