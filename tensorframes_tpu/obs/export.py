"""Telemetry export: periodic per-process snapshots for fleet federation.

Every observability surface so far is per-process, but the fleet
(:mod:`tensorframes_tpu.serve.fleet`), the distributed job workers
(:mod:`tensorframes_tpu.engine.dist_jobs`), and any driver each run in
their OWN process — one pane of glass needs their registries in one
place. This module is the write side of that plane: each process with a
live sampler periodically serializes its metric registry
(:func:`~.metrics.snapshot`) and the raw tier of its time-series store
into ``<telemetry_dir>/<proc-id>.json``. The read side
(:mod:`.aggregate`) merges whatever snapshot files it finds.

Design points, all borrowed from the repo's existing durable surfaces:

- **atomic rename** — a snapshot is written to a ``.tmp-<pid>`` sibling
  and ``os.replace``'d into place, so readers only ever see whole files
  (the tune store and job journal write the same way);
- **schema version** — the payload carries ``schema``; the aggregator
  skips files from a different schema instead of guessing;
- **mtime staleness** — liveness is the FILE's mtime, not anything in
  the payload: a kill -9'd process stops refreshing its file, and the
  aggregator flags it ``stale`` after ``Config.telemetry_stale_after_s``
  while keeping its last counters visible (crashed workers' totals
  still count);
- **rides the sampler tick** — :func:`autoexport` is called from
  ``timeseries.sample_once`` exactly like ``programs.autopersist``,
  throttled to ``Config.obs_export_interval_s``; no extra thread;
- **kill-switch parity** — under ``TFT_OBS=0`` /
  ``Config(observability=False)`` nothing touches the disk.

The module also owns process **identity**: a ``build.info``-style gauge
(proc id, pid, role, package version, device kind — value 1.0, the
Prometheus ``build_info`` idiom) that federation uses to label merged
series and ``/statusz`` shows. Roles: ``serve-replica`` (a
``ScoringServer`` with an engine), ``job-worker``
(``dist_jobs.run_worker``), ``driver`` (everything else, the default).
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import get_logger
from .metrics import counter as _counter, enabled, gauge as _gauge, registry

__all__ = [
    "SCHEMA_VERSION",
    "autoexport",
    "export_snapshot",
    "identity",
    "proc_id",
    "set_identity",
    "telemetry_dir",
]

logger = get_logger("obs.export")

#: bump on any incompatible snapshot-layout change; the aggregator
#: skips files whose schema differs (never guesses)
SCHEMA_VERSION = 1

#: newest tier-0 points exported per series — bounds snapshot size; the
#: fleet view is an operational window, not an archive (each process
#: keeps its own full tiered history locally)
_EXPORT_POINTS = 256

_m_exports = _counter(
    "obs.telemetry_exports_total",
    "Telemetry snapshots written to the fleet telemetry directory",
)
_g_identity = _gauge(
    "build.info",
    "Process identity (value is always 1 for the current role): proc "
    "id, pid, role serve-replica|job-worker|driver, package version, "
    "device kind — what federation labels merged series with",
    labels=("proc", "pid", "role", "version", "device"),
)

_lock = threading.Lock()
_role = "driver"
_identity_pid: Optional[int] = None  # pid the identity gauge was set for
_device_kind: Optional[str] = None
_last_export = 0.0  # monotonic, throttles autoexport


def proc_id() -> str:
    """Stable-ish process identity for the snapshot filename and the
    identity gauge: ``$TFT_PROC_ID`` when set (fleet replicas and job
    workers get deterministic ids that way), else ``<host>-<pid>``."""
    explicit = os.environ.get("TFT_PROC_ID", "")
    if explicit:
        return explicit
    return f"{socket.gethostname()}-{os.getpid()}"


def _package_version() -> str:
    try:
        from .. import __version__

        return str(__version__)
    except Exception:
        return "unknown"


def _detect_device_kind() -> str:
    """Device kind of the default backend, cached; ``"unknown"`` when
    jax has no initialized/initializable backend (a bare exporter
    process must not be forced through backend init just to label
    itself)."""
    global _device_kind
    if _device_kind is None:
        try:
            import jax

            _device_kind = str(jax.devices()[0].device_kind)
        except Exception:
            _device_kind = "unknown"
    return _device_kind


def set_identity(role: str) -> Dict[str, Any]:
    """Declare this process's role and (re)publish the identity gauge.

    Idempotent; a role CHANGE zeroes the former role's series first (the
    gauge has no per-series removal, and two role series at 1.0 would
    double-count the process in any fleet sum)."""
    global _role, _identity_pid
    with _lock:
        old = _role
        _role = str(role)
        if old != _role and _identity_pid is not None:
            _g_identity.set(
                0.0, proc=proc_id(), pid=str(_identity_pid), role=old,
                version=_package_version(), device=_detect_device_kind(),
            )
        _identity_pid = os.getpid()
        _g_identity.set(
            1.0, proc=proc_id(), pid=str(_identity_pid), role=_role,
            version=_package_version(), device=_detect_device_kind(),
        )
    return identity()


def identity() -> Dict[str, Any]:
    """This process's identity labels — the ``/statusz`` ``identity``
    block and the per-proc header federation attaches to merged data."""
    return {
        "proc": proc_id(),
        "pid": os.getpid(),
        "role": _role,
        "version": _package_version(),
        "device": _detect_device_kind(),
        "host": socket.gethostname(),
    }


def telemetry_dir() -> str:
    """The shared snapshot directory: ``Config.telemetry_dir``, else
    ``$TFT_TELEMETRY_DIR``, else ``""`` (export disabled)."""
    from ..utils.config import get_config

    return get_config().telemetry_dir or os.environ.get(
        "TFT_TELEMETRY_DIR", ""
    )


def _snapshot_payload(now: float) -> Dict[str, Any]:
    from . import timeseries as _ts

    series: Dict[str, List[List[float]]] = {}
    st = _ts.store()
    for name in st.names():
        pts = st.points(name, 0)[-_EXPORT_POINTS:]
        if pts:
            series[name] = [[round(ts, 3), v] for ts, v in pts]
    return {
        "schema": SCHEMA_VERSION,
        "proc": proc_id(),
        "pid": os.getpid(),
        "ts_unix": round(now, 3),
        "identity": identity(),
        "metrics": registry().snapshot(),
        "series": series,
        "last_tick_ts": _ts.last_tick_ts(),
    }


def export_snapshot(
    dir: Optional[str] = None, now: Optional[float] = None
) -> Optional[str]:
    """Write this process's snapshot; returns the path, or ``None``
    when export is disabled (no directory / kill switch) or the write
    failed (logged — telemetry must never take down what it observes)."""
    if not enabled():
        return None
    target_dir = dir or telemetry_dir()
    if not target_dir:
        return None
    ts = time.time() if now is None else now
    try:
        payload = _snapshot_payload(ts)
        os.makedirs(target_dir, exist_ok=True)
        fname = re.sub(r"[^A-Za-z0-9_.-]", "_", payload["proc"]) + ".json"
        path = os.path.join(target_dir, fname)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        _m_exports.inc()
        return path
    except Exception:
        logger.warning("telemetry export failed", exc_info=True)
        return None


def autoexport(now: Optional[float] = None) -> Optional[str]:
    """Throttled :func:`export_snapshot` for the sampler tick: at most
    one write per ``Config.obs_export_interval_s`` (re-read each call,
    so retunes apply live). No-op when export is disabled."""
    global _last_export
    if not enabled() or not telemetry_dir():
        return None
    from ..utils.config import get_config

    interval = get_config().obs_export_interval_s
    mono = time.monotonic()
    if mono - _last_export < max(0.0, interval):
        return None
    _last_export = mono
    return export_snapshot(now=now)
