"""Observability: the metrics registry and span tracing.

The reference delegated all runtime visibility to Spark's UI and task
metrics; this package is the standalone replacement — counters/gauges/
histograms (:mod:`.metrics`) and nested spans with a JSONL event log and
Perfetto forwarding (:mod:`.tracing`). The engine, frame, serving,
failure, and packer layers publish into the default registry at module
import; ``ScoringServer`` exports it as a Prometheus scrape on its Arrow
port (``GET /metrics``). See ``docs/observability.md`` for the metric
catalog and span conventions.

Kill switch: ``TFT_OBS=0`` in the environment, or
``tft.utils.set_config(observability=False)``.
"""

from .metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    enabled,
    gauge,
    histogram,
    registry,
    render_prometheus,
    snapshot,
)
from .tracing import (
    Span,
    TraceContext,
    current_span,
    current_trace,
    event,
    new_trace,
    set_annotations,
    set_trace_sink,
    span,
    trace_sink,
    use_trace,
)
from . import flight
from . import programs
from . import slo
from . import timeseries
from . import export
from . import aggregate
from . import drift
from . import requests

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "counter",
    "gauge",
    "histogram",
    "registry",
    "snapshot",
    "render_prometheus",
    "enabled",
    "Span",
    "TraceContext",
    "span",
    "event",
    "current_span",
    "current_trace",
    "new_trace",
    "use_trace",
    "set_annotations",
    "set_trace_sink",
    "trace_sink",
    "flight",
    "programs",
    "slo",
    "timeseries",
    "export",
    "aggregate",
    "drift",
    "requests",
]
