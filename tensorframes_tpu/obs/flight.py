"""Crash flight recorder: bounded in-memory event rings + debug bundles.

Counters say *how often*; spans say *how long*; neither says *what the
last 500 interesting things were* when an engine dies at 3am. The flight
recorder is that third signal: a set of bounded per-subsystem ring
buffers (``collections.deque`` with ``maxlen`` — append is O(1), ~2 µs
per event, memory strictly bounded) fed by the failure-adjacent paths:

- ``trace``   — completed spans / point events (only while
  :func:`capture_spans` is on — span capture makes every ``span()``
  live, which costs ~2-3 µs each on dispatch paths, so it is a consumer
  you attach deliberately, exactly like the JSONL sink);
- ``chaos``   — every injected fault (``utils/chaos.py``);
- ``retries`` — transient-failure retries and exhaustions
  (``utils/failures.py``);
- ``preemptions`` — preempt-and-requeue evictions;
- ``fences``  — distributed-job write-fence rejects
  (``engine/dist_jobs.py``);
- ``serve`` / ``fleet`` / ``jobs`` / ``serving`` — subsystem lifecycle
  events (engine fatal/restart, replica fence/replay, block quarantine,
  request completions);
- ``slo`` / ``drift`` — objective breach/recovery and drift
  shift/recovery transitions (``obs/slo.py``, ``obs/drift.py``).

On a terminal event — engine fatal step, ``restart()``, block
quarantine, write-fence reject — :func:`dump_bundle` snapshots the whole
story to ONE JSON file (a **debug bundle**): every ring's contents, the
full metrics snapshot, the caller's health report, the resolved
``Config``, and the active chaos spec. Bundles are listed by
``GET /statusz``, linked from ``quarantine.json``, and surfaced in
``GET /healthz`` (``interop/serving.py``), so the artifact that explains
a failure is one click from the probe that noticed it. Layout and the
operator cookbook: ``docs/observability.md``.

Kill-switch parity: ``TFT_OBS=0`` / ``Config(observability=False)``
makes :func:`record` a no-op (one predicate — the same gate as the
metrics registry) and :func:`dump_bundle` return ``None``. Nothing here
ever runs inside a traced/compiled function, so the recorder adds zero
compiled programs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from .metrics import counter as _counter, enabled, snapshot

__all__ = [
    "capture_spans",
    "dump_bundle",
    "last_bundle",
    "record",
    "record_span",
    "recent_bundles",
    "reset",
    "rings",
    "span_capture_on",
]

logger = get_logger("obs.flight")

_m_bundles = _counter(
    "obs.debug_bundles_total",
    "Debug bundles dumped by the flight recorder, by trigger reason",
    labels=("reason",),
)

#: events kept per subsystem ring (each event is a small tuple; 512
#: events ≈ tens of KB per subsystem). A malformed or non-positive
#: TFT_FLIGHT_EVENTS falls back — a typo'd knob must not crash
#: `import tensorframes_tpu` (this module loads with the package).
def _env_ring_len() -> int:
    try:
        n = int(os.environ.get("TFT_FLIGHT_EVENTS", "512") or 512)
    except ValueError:
        return 512
    return n if n > 0 else 512


_RING_LEN = _env_ring_len()

_rings_lock = threading.Lock()
_rings: Dict[str, Deque[Tuple[float, str, Dict[str, Any]]]] = {}

#: recent bundle registry for /statusz and /healthz
_bundles: Deque[Dict[str, Any]] = deque(maxlen=16)
#: (reason, dir) -> last dump monotonic time; a crash LOOP must not
#: write hundreds of identical bundles per second
_last_dump: Dict[Tuple[str, str], float] = {}
_DUMP_DEBOUNCE_S = 1.0


def _ring(subsystem: str) -> Deque[Tuple[float, str, Dict[str, Any]]]:
    ring = _rings.get(subsystem)
    if ring is None:
        with _rings_lock:
            ring = _rings.setdefault(subsystem, deque(maxlen=_RING_LEN))
    return ring


def record(subsystem: str, kind: str, **data) -> None:
    """Append one event to ``subsystem``'s ring. ~2 µs: one gate check,
    one ``time.time()``, one bounded-deque append (appends on a deque
    are thread-safe under the GIL; the ring needs no lock of its own).
    No-op when observability is off."""
    if not enabled():
        return
    _ring(subsystem).append((time.time(), kind, data))


def record_span(
    name: str,
    trace_id: Optional[str],
    span_id: str,
    dur_s: float,
    attrs: Dict[str, Any],
) -> None:
    """The tracing layer's feed (``obs/tracing.py:_emit``): one closed
    span or point event into the ``trace`` ring."""
    if not enabled():
        return
    _ring("trace").append(
        (
            time.time(),
            "span",
            {
                "name": name,
                "trace_id": trace_id,
                "span_id": span_id,
                "dur_s": dur_s,
                "attrs": attrs,
            },
        )
    )


_capture_spans = False


def capture_spans(on: bool) -> None:
    """Make every ``span()`` live and mirror it into the ``trace`` ring
    (a span CONSUMER, like the JSONL sink — span creation then costs
    ~2-3 µs each on the dispatch paths it instruments). The bundle's
    ``trace`` ring is empty unless this (or a sink with spans feeding
    other rings) is on."""
    global _capture_spans
    _capture_spans = bool(on)
    from . import tracing as _tracing

    _tracing._set_flight_capture(_capture_spans)


def span_capture_on() -> bool:
    return _capture_spans


def rings() -> Dict[str, List[Dict[str, Any]]]:
    """Every ring's contents as JSON-ready dicts, oldest first."""
    with _rings_lock:
        names = list(_rings)
    out: Dict[str, List[Dict[str, Any]]] = {}
    for name in names:
        out[name] = [
            {"ts": ts, "kind": kind, **_jsonable(data)}
            for ts, kind, data in list(_rings[name])
        ]
    return out


def _jsonable(data: Dict[str, Any]) -> Dict[str, Any]:
    try:
        json.dumps(data)
        return data
    except (TypeError, ValueError):
        return {k: str(v) for k, v in data.items()}


def reset() -> None:
    """Drop every ring and the bundle registry (test isolation)."""
    with _rings_lock:
        _rings.clear()
    _bundles.clear()
    _last_dump.clear()


def recent_bundles() -> List[Dict[str, Any]]:
    """The last bundles dumped by this process, newest first:
    ``[{"ts_unix", "reason", "path"}, ...]`` — what ``/statusz`` and
    ``/healthz`` surface."""
    return list(reversed(_bundles))


def last_bundle() -> Optional[Dict[str, Any]]:
    return _bundles[-1] if _bundles else None


def _bundle_dir(explicit: Optional[str]) -> str:
    if explicit:
        return explicit
    from ..utils.config import get_config

    cfg_dir = get_config().debug_bundle_dir
    if cfg_dir:
        return cfg_dir
    return os.environ.get("TFT_DEBUG_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "tensorframes_tpu", "debug"
    )


def dump_bundle(
    reason: str,
    *,
    health: Optional[Dict[str, Any]] = None,
    extra: Optional[Dict[str, Any]] = None,
    dir: Optional[str] = None,
    debounce_key: Optional[str] = None,
    series_prefix: Optional[str] = None,
    series_window_s: float = 300.0,
) -> Optional[str]:
    """Write one debug bundle and return its path (``None`` when
    observability is off, the same ``reason``+directory dumped within
    the last second — a crash loop must not flood the disk — or the
    write failed; a recorder that crashes the failure path it documents
    would be worse than no recorder). ``debounce_key`` widens the
    debounce identity: DISTINCT failures of one reason in quick
    succession (e.g. several blocks quarantining milliseconds apart)
    each deserve their bundle — pass the failing unit's id so only true
    repeats are suppressed.

    ``series_prefix`` additionally captures the triggering subsystem's
    recent time-series trajectory (every stored series under the
    prefix, trailing ``series_window_s``) — a fatal's bundle then shows
    the minutes INTO the failure, not just the terminal state.

    The bundle is a single JSON file::

        {"reason": ..., "ts_unix": ..., "host": ..., "pid": ...,
         "rings": {subsystem: [events...]},   # the flight recorder
         "metrics": {...},                    # obs.snapshot()
         "timeseries": {...},                 # windowed series (opt-in)
         "health": {...},                     # caller's health() report
         "config": {...},                     # resolved Config
         "chaos_spec": "...",                 # active chaos schedule
         "extra": {...}}                      # trigger-specific context

    Directory precedence: ``dir`` argument, ``Config.debug_bundle_dir``,
    ``TFT_DEBUG_DIR``, ``~/.cache/tensorframes_tpu/debug``."""
    if not enabled():
        return None
    try:
        root = _bundle_dir(dir)
        key = (
            reason if debounce_key is None
            else f"{reason}:{debounce_key}",
            root,
        )
        now = time.monotonic()
        last = _last_dump.get(key)
        if last is not None and now - last < _DUMP_DEBOUNCE_S:
            return None
        _last_dump[key] = now
        os.makedirs(root, exist_ok=True)
        from ..utils import chaos as _chaos
        from ..utils.config import get_config

        ts = time.time()
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(ts))
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in reason
        )
        path = os.path.join(
            root,
            f"bundle-{stamp}-{safe_reason}-{os.getpid()}-{int(ts * 1e3) % 100000}.json",
        )
        bundle = {
            "version": 1,
            "reason": reason,
            "ts_unix": ts,
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "rings": rings(),
            "metrics": snapshot(),
            "health": health,
            "config": dataclasses.asdict(get_config()),
            "chaos_spec": _chaos.active_spec(),
            "extra": extra or {},
        }
        if series_prefix is not None:
            from . import timeseries as _ts

            bundle["timeseries"] = {
                "prefix": series_prefix,
                "window_s": series_window_s,
                "series": _ts.store().to_dict(
                    prefix=series_prefix, window_s=series_window_s
                ),
            }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        os.replace(tmp, path)
        _bundles.append(
            {"ts_unix": ts, "reason": reason, "path": path}
        )
        _m_bundles.inc(reason=reason)
        logger.warning("flight recorder: debug bundle dumped: %s", path)
        return path
    except Exception:
        logger.warning(
            "flight recorder: bundle dump for %r failed", reason,
            exc_info=True,
        )
        return None
