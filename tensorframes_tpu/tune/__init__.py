"""Self-tuning performance layer: online autotuner + persisted cost
model serving tuned configs fleet-wide (ROADMAP item 3).

Every perf-critical constant in the stack used to be a hand-measured
static — the ``_BEST_BLOCKS`` tile tables, ``paged_page_size_hint``'s
serving default, ``Config.transfer_chunk_bytes`` / ``transfer_streams``,
``serve_prefill_chunk_tokens``, the map-rows block-row budget — and the
r05 bench rounds showed those go stale the moment link weather or
shapes change. This package replaces them with three cooperating
pieces:

- :mod:`.search` — the online autotuner: on first sight of a (shape,
  dtype, op-kind) signature it micro-benchmarks a candidate grid inside
  the existing retry/chaos envelopes, picks the winner by median wall,
  and installs it for all subsequent dispatches of that signature;
- :mod:`.store` — the persisted tuning database: JSONL next to the XLA
  compile cache, atomic-rename writes, schema-versioned, keyed by
  signature + device kind; winners survive restarts and are shared
  fleet-wide through the same file (mtime re-read);
- :mod:`.model` — the learned cost predictor (ridge/analytic hybrid
  over the observatory's per-program FLOP/byte/wall records) that ranks
  the grid so measured trials cover only the top-K predicted configs.

Consumers: ``ops/attention.py`` (tile lookup — the static tables become
the seed prior), ``frame/transfer.py`` (chunk bytes × streams),
``serve/engine.py`` (page size + prefill chunk), ``engine/ops.py``
(block-row budget). Knobs: ``Config.autotune`` /
``Config.tune_mode="off"|"cached"|"online"`` / ``Config.tune_budget_s``
and the ``TFT_TUNE=0`` kill switch. The contract: tuning changes which
config runs, **never** what it computes — every tuned surface is
byte-identity-tested against its static default. See docs/tuning.md.
"""

from .model import (
    CostModel,
    default_model,
    load_cost_records,
    per_chip_records,
)
from .search import (
    Tuner,
    clear,
    in_trial,
    jobs_signature,
    lookup,
    mode,
    pin,
    rank_tp_layouts,
    render_table,
    reset,
    serve_signature,
    snapshot,
    tune_serve_knobs,
    tuner,
)
from .store import SCHEMA_VERSION, TuneStore, device_kind, store_path

__all__ = [
    "CostModel",
    "SCHEMA_VERSION",
    "TuneStore",
    "Tuner",
    "clear",
    "default_model",
    "device_kind",
    "in_trial",
    "jobs_signature",
    "load_cost_records",
    "lookup",
    "mode",
    "per_chip_records",
    "pin",
    "rank_tp_layouts",
    "render_table",
    "reset",
    "serve_signature",
    "snapshot",
    "store_path",
    "tune_serve_knobs",
    "tuner",
]
