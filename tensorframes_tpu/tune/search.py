"""Online autotuner: measure the candidate grid once, serve the winner
forever.

On first sight of a ``(surface, signature, device-kind)`` the tuner —
in ``online`` mode — runs a short **seeded micro-benchmark** over the
surface's candidate grid (flash tile sizes, transfer chunk bytes ×
streams, serve page size / prefill chunk tokens, map-rows block-row
budgets), picks the winner by **median wall**, installs it for every
subsequent dispatch of that signature, and persists it to the shared
:class:`~tensorframes_tpu.tune.store.TuneStore` so other processes —
and future ones — serve it from cache with zero trials.

Search is budgeted and model-pruned: the learned cost predictor
(:mod:`.model`) ranks the grid and only the top-K predicted candidates
are measured (never more than half the full grid), each inside
``Config.tune_budget_s`` wall-clock for the whole signature. The static
default is ALWAYS measured first, so an exhausted budget or a flaky
grid degrades to "keep the default", never to a blind winner.

Trials run inside the same envelopes as every other dispatch: each
timed attempt passes the ``tune.trial`` chaos site and runs under
``run_with_retries`` (a transient fault retries the trial; a fatal one
skips the candidate). While a tuning pass is live, every lookup —
from the trial's own thread or any other (trials may push work onto
engine threads) — is READ-ONLY: installed winners still apply, so the
trial measures the configuration steady state will run with, but no
nested search can start, so a transfer trial can upload bytes without
recursively tuning the transfer layer.

The hard contract, enforced by tests/test_tune.py: **tuning changes
which config runs, never what it computes** — consumer grids only offer
candidates whose results are byte-identical to the static default's
(see docs/tuning.md for what that constrains per surface).
"""

from __future__ import annotations

import os
import statistics
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..obs.metrics import counter as _counter
from ..obs.metrics import histogram as _histogram
from ..utils.logging import get_logger
from .model import CostModel, default_model
from .store import TuneStore, device_kind, make_key

__all__ = [
    "Tuner",
    "clear",
    "jobs_signature",
    "lookup",
    "mode",
    "pin",
    "rank_tp_layouts",
    "render_table",
    "reset",
    "snapshot",
    "tune_serve_knobs",
    "tuner",
]

logger = get_logger("tune")

_m_trials = _counter(
    "tune.trials_total",
    "Autotuner micro-benchmark candidates measured, by surface and "
    "signature",
    labels=("surface", "signature"),
)
_m_winners = _counter(
    "tune.winners_total",
    "Tuned winners installed and persisted by this process, by surface",
    labels=("surface",),
)
_m_hits = _counter(
    "tune.cache_hits_total",
    "Tuner lookups served from the persisted store or the in-process "
    "memo without running a trial, by surface",
    labels=("surface",),
)
_h_err = _histogram(
    "tune.predicted_error_ratio",
    "Cost-model honesty per measured trial: |predicted - measured| / "
    "measured wall",
)

#: re-entrancy guard: lookups made from inside a trial body must never
#: START a tuning pass (a transfer trial must not recursively tune
#: transfer). Thread-local for the common same-thread case, PLUS a
#: process-global depth for trials that spawn work onto other threads
#: (a serve-knob trial's engine steps on its own daemon thread) —
#: while ANY tuning pass is live, every lookup is read-only.
_tls = threading.local()
_tuning_depth = 0
_tuning_lock = threading.Lock()

_MODES = ("off", "cached", "online")
_warned_mode = set()


def mode() -> str:
    """The active tuning mode: ``"off"`` | ``"cached"`` | ``"online"``.
    ``TFT_TUNE=0`` in the environment is the kill switch (checked live,
    so the bench-regression gate can pin it per subprocess); then
    ``Config.autotune`` (master switch) and ``Config.tune_mode``."""
    if os.environ.get("TFT_TUNE", "") == "0":
        return "off"
    from ..utils.config import get_config

    cfg = get_config()
    if not cfg.autotune:
        return "off"
    m = cfg.tune_mode
    if m not in _MODES:
        if m not in _warned_mode:
            _warned_mode.add(m)
            logger.warning(
                "unknown Config.tune_mode %r (expected one of %s); "
                "tuning disabled", m, _MODES,
            )
        return "off"
    return m


def in_trial() -> bool:
    """True while a tuning pass is live anywhere in the process: this
    thread is inside a trial body, OR any tuner is mid-search (trials
    may run work on other threads — the engine's stepping thread —
    which must not nest a second search inside the one being timed)."""
    if getattr(_tls, "in_trial", False):
        return True
    return _tuning_depth > 0


class Tuner:
    """One store-backed tuner. The module singleton (:func:`tuner`) is
    what the consumers use; tests may build private instances against
    their own store paths."""

    def __init__(
        self,
        store: Optional[TuneStore] = None,
        model: Optional[CostModel] = None,
    ):
        self.store = store if store is not None else TuneStore()
        self._model = model
        #: an explicitly-injected model (tests, operators) is
        #: authoritative for EVERY view, per-chip included
        self._model_injected = model is not None
        self._model_per_chip: Optional[CostModel] = None
        self._lock = threading.Lock()
        #: resolved winners, keyed (store path, surface, signature,
        #: device) -> (config, source). "Installed for all subsequent
        #: dispatches": once resolved, a signature is stable for this
        #: process's lifetime (path in the key keeps tests that repoint
        #: TFT_TUNE_FILE isolated without a reset)
        self._installed: Dict[tuple, tuple] = {}

    # -- model -------------------------------------------------------------

    def model(self, per_chip: bool = False) -> CostModel:
        """The tuner's cached cost model — fit once per Tuner lifetime
        (``programs.jsonl`` read + ridge fit are not per-call work).
        ``per_chip=True`` serves the multi-device-normalized fit
        (:func:`~tensorframes_tpu.tune.model.per_chip_records`) the
        tensor-parallel layout ranker uses; a model injected at
        construction is authoritative for both views."""
        with self._lock:
            if per_chip and not self._model_injected:
                if self._model_per_chip is None:
                    self._model_per_chip = default_model(per_chip=True)
                return self._model_per_chip
            if self._model is None:
                self._model = default_model()
            return self._model

    # -- resolution --------------------------------------------------------

    def lookup(
        self,
        surface: str,
        signature: str,
        default: Dict[str, Any],
        *,
        grid: Optional[Sequence[Dict[str, Any]]] = None,
        feats: Optional[Callable[[Dict[str, Any]], tuple]] = None,
        trial: Optional[Callable[[Dict[str, Any]], None]] = None,
        budget_s: Optional[float] = None,
        repeats: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Resolve the active config for ``(surface, signature)``.

        Returns ``default`` merged under the winner (winner keys win),
        so consumers always see every knob they asked about. ``off``
        mode and ``cached`` misses return ``default`` as-is; lookups
        made while a tuning pass is live are read-only (winners serve,
        no nested search starts). ``online`` misses run the measured
        search when ``trial`` is supplied and ``grid`` offers at least
        one non-default candidate;
        surfaces with no safe in-process trial (the serving knobs at
        engine init) pass ``trial=None`` and stay cache-only — their
        winners come from :func:`tune_serve_knobs` / ``bench.py
        autotune`` / an operator pin."""
        m = mode()
        if m == "off":
            return dict(default)
        # a lookup made from INSIDE a trial body must never tune (that
        # would recurse), but it SHOULD see already-installed winners —
        # trials must measure the configuration steady state will run
        # with, not a defaults-only world that biases winner selection
        trialing = in_trial()
        dev = device_kind()
        key = make_key(surface, signature, dev)
        memo_key = (self.store.path(), surface, signature, dev)
        with self._lock:
            hit = self._installed.get(memo_key)
        if hit is not None:
            if not trialing:
                _m_hits.inc(surface=surface)
            return {**default, **hit[0]}
        rec = self.store.get(key)
        if rec is not None:
            cfg = dict(rec.get("config") or {})
            with self._lock:
                self._installed[memo_key] = (cfg, "store")
            if not trialing:
                _m_hits.inc(surface=surface)
            return {**default, **cfg}
        if m != "online" or trial is None or trialing:
            return dict(default)
        rest = [c for c in (grid or []) if c != default]
        if not rest:
            # nothing to choose between: measuring the lone default and
            # fsync'ing a store write on the request path buys nothing
            return dict(default)
        winner = self._tune(
            surface, signature, key, memo_key, default,
            rest, feats, trial, budget_s, repeats,
        )
        return {**default, **winner}

    # -- the measured search ----------------------------------------------

    def _tune(
        self,
        surface: str,
        signature: str,
        key: str,
        memo_key: tuple,
        default: Dict[str, Any],
        rest: List[Dict[str, Any]],
        feats,
        trial,
        budget_s: Optional[float],
        repeats: Optional[int],
    ) -> Dict[str, Any]:
        global _tuning_depth
        with _tuning_lock:
            _tuning_depth += 1
        try:
            return self._tune_locked(
                surface, signature, key, memo_key, default, rest,
                feats, trial, budget_s, repeats,
            )
        finally:
            with _tuning_lock:
                _tuning_depth -= 1

    def _tune_locked(
        self,
        surface: str,
        signature: str,
        key: str,
        memo_key: tuple,
        default: Dict[str, Any],
        rest: List[Dict[str, Any]],
        feats,
        trial,
        budget_s: Optional[float],
        repeats: Optional[int],
    ) -> Dict[str, Any]:
        from ..utils.config import get_config

        cfg = get_config()
        budget = cfg.tune_budget_s if budget_s is None else budget_s
        n_rep = max(1, cfg.tune_trials if repeats is None else repeats)
        # the static default is ALWAYS candidate 0 — the winner can
        # never be a config that measured worse than what we had
        candidates: List[Dict[str, Any]] = [dict(default)]
        predicted: Dict[int, float] = {}
        if rest:
            # the learned ranker prunes: measured trials cover only the
            # top-K predicted configs, and never more than half of the
            # full grid (default included in the count). Tiny grids
            # (<= 3 candidates) measure in full — halving a 2-entry
            # grid would mean never measuring the alternative at all
            full = len(rest) + 1
            if full <= 3:
                top_k = full
            else:
                top_k = max(1, min(int(cfg.tune_top_k), full // 2))
            if feats is not None:
                ranked = self.model().rank(rest, feats)
            else:
                ranked = [(c, float("inf")) for c in rest]
            import math

            for cand, pred in ranked[: max(0, top_k - 1)]:
                # feats-less searches (and candidates whose features
                # raised) rank at +inf — that is "no prediction", not a
                # prediction to hold the honesty histogram against
                # (observing inf would poison the scrape's _sum forever)
                if math.isfinite(pred):
                    predicted[len(candidates)] = pred
                candidates.append(cand)
            if feats is not None:
                try:
                    f, b, d = feats(dict(default))
                    predicted[0] = self.model().predict(f, b, d)
                except Exception:
                    pass
        deadline = time.monotonic() + max(0.0, float(budget))
        walls: List[Optional[float]] = []
        for i, cand in enumerate(candidates):
            if i > 0 and time.monotonic() > deadline:
                logger.info(
                    "tune %s[%s]: budget %.2fs exhausted after %d/%d "
                    "candidates", surface, signature, budget, i,
                    len(candidates),
                )
                break
            try:
                wall = self._measure(
                    surface, signature, cand, trial, n_rep, deadline
                )
            except Exception as e:
                logger.warning(
                    "tune %s[%s]: candidate %r failed (%s: %s); skipped",
                    surface, signature, cand, type(e).__name__, e,
                )
                walls.append(None)
                continue
            walls.append(wall)
            pred = predicted.get(i)
            if pred is not None and wall > 0:
                _h_err.observe(abs(pred - wall) / wall)
        measured = [
            (w, i) for i, w in enumerate(walls) if w is not None
        ]
        if not measured or walls[0] is None:
            # nothing measured cleanly — or the DEFAULT's own trial
            # failed: a candidate that was never compared against the
            # default must not become a fleet-wide winner ("degrades to
            # keep the default, never a blind winner"). Store nothing;
            # a healthier pass may tune this signature later.
            return dict(default)
        best_wall, best_i = min(measured)
        winner = dict(candidates[best_i])
        with self._lock:
            self._installed[memo_key] = (winner, "tuned")
        self.store.put(
            key, winner,
            wall_s=best_wall,
            meta={
                "trials": len(measured),
                "grid": len(candidates),
                "default_wall_s": round(walls[0], 6)
                if walls and walls[0] is not None
                else None,
                "model": self.model().source if feats is not None else None,
            },
        )
        _m_winners.inc(surface=surface)
        logger.info(
            "tune %s[%s]: winner %r at %.4fs median over %d candidate(s)",
            surface, signature, winner, best_wall, len(measured),
        )
        return winner

    def _measure(
        self,
        surface: str,
        signature: str,
        cand: Dict[str, Any],
        trial,
        repeats: int,
        deadline: float,
    ) -> float:
        """Median wall of up to ``repeats`` timed trial runs (plus one
        untimed warmup that pays any compile), each attempt behind the
        ``tune.trial`` chaos site inside its own retry window. The
        budget deadline binds BETWEEN repeats too — one slow candidate
        must not overshoot the signature budget by repeats × wall — but
        every started candidate completes at least one timed run, so a
        measurement always exists."""
        from ..utils import run_with_retries
        from ..utils.chaos import site as _chaos_site

        def attempt() -> float:
            _chaos_site("tune.trial")
            _tls.in_trial = True
            t0 = time.perf_counter()
            try:
                trial(cand)
            finally:
                _tls.in_trial = False
            return time.perf_counter() - t0

        what = f"tune.trial {surface}[{signature}]"
        run_with_retries(attempt, what=f"{what} warmup")
        walls = []
        for _ in range(repeats):
            walls.append(run_with_retries(attempt, what=what))
            if time.monotonic() > deadline:
                break
        # one trial == one measured candidate (the acceptance criterion
        # "trials-per-signature <= half of full-grid" counts candidates,
        # not repeats)
        _m_trials.inc(surface=surface, signature=signature)
        return float(statistics.median(walls))

    # -- operator verbs ----------------------------------------------------

    def pin(
        self,
        surface: str,
        signature: str,
        config: Dict[str, Any],
        device: Optional[str] = None,
    ) -> None:
        """Force a winner (no measurement): installed in-process and
        persisted, exactly as if it had been tuned. The cookbook verb
        for carrying a winner from a bench box to a fleet, and what the
        byte-identity tests use to exercise tuned paths
        deterministically."""
        dev = device if device is not None else device_kind()
        key = make_key(surface, signature, dev)
        self.store.put(key, dict(config), meta={"pinned": True})
        with self._lock:
            self._installed[
                (self.store.path(), surface, signature, dev)
            ] = (dict(config), "pinned")

    def clear(self, surface: Optional[str] = None) -> int:
        """Forget winners (one surface's, or all): cleared from the
        store AND the in-process memo, so the next lookup re-tunes."""
        removed = self.store.clear(surface)
        with self._lock:
            if surface is None:
                self._installed.clear()
            else:
                for k in [
                    k for k in self._installed if k[1] == surface
                ]:
                    del self._installed[k]
        return removed

    def snapshot(self) -> List[Dict[str, Any]]:
        """Every resolved-or-stored winner, for ``/statusz`` and
        ``explain(analyze=True)``: in-process installations first
        (source ``tuned``/``pinned``/``store``), then store entries not
        yet consulted by this process (source ``persisted``)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            installed = dict(self._installed)
        seen = set()
        for (path, surface, signature, dev), (cfg, src) in sorted(
            installed.items()
        ):
            out.append(
                {
                    "surface": surface,
                    "signature": signature,
                    "device": dev,
                    "config": dict(cfg),
                    "source": src,
                }
            )
            seen.add((surface, signature, dev))
        try:
            for key, rec in sorted(self.store.entries().items()):
                ident = (
                    rec.get("surface"), rec.get("signature"),
                    rec.get("device"),
                )
                if ident in seen:
                    continue
                out.append(
                    {
                        "surface": rec.get("surface"),
                        "signature": rec.get("signature"),
                        "device": rec.get("device"),
                        "config": dict(rec.get("config") or {}),
                        "source": "persisted",
                        "wall_s": rec.get("wall_s"),
                    }
                )
        except Exception:
            pass
        return out


# ---------------------------------------------------------------------------
# module singleton + convenience verbs
# ---------------------------------------------------------------------------

_singleton_lock = threading.Lock()
_singleton: Optional[Tuner] = None


def tuner() -> Tuner:
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = Tuner()
        return _singleton


def reset() -> None:
    """Drop the singleton (test isolation: fresh memo, fresh model,
    store path re-resolved)."""
    global _singleton
    with _singleton_lock:
        _singleton = None


def lookup(surface, signature, default, **kw) -> Dict[str, Any]:
    return tuner().lookup(surface, signature, default, **kw)


def pin(surface, signature, config, device=None) -> None:
    tuner().pin(surface, signature, config, device)


def clear(surface: Optional[str] = None) -> int:
    return tuner().clear(surface)


def snapshot() -> List[Dict[str, Any]]:
    return tuner().snapshot()


def render_table() -> str:
    """Plain-text tuned-config table for ``explain(analyze=True)``."""
    rows = snapshot()
    lines = [f"== Tuned configs == (mode={mode()})"]
    if not rows:
        lines.append(" (no tuned winners installed or stored)")
        return "\n".join(lines)
    for r in rows:
        cfg = " ".join(f"{k}={v}" for k, v in sorted(r["config"].items()))
        lines.append(
            f" {r['surface']}[{r['signature']}] @{r['device']} "
            f"{cfg} ({r['source']})"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the serving-knob search
# ---------------------------------------------------------------------------


def jobs_signature() -> str:
    """The distributed-job knob signature. Lease TTL trades liveness
    (how fast a dead worker's blocks reclaim) against safety margin for
    slow-but-alive workers — a property of the HOST (filesystem
    latency, scheduler jitter), not of any workload shape, so one row
    per device kind (the store keys on device separately) is the right
    granularity."""
    return "host=v1"


def rank_tp_layouts(
    model,
    *,
    max_seq_len: int,
    max_slots: int = 8,
    degrees: Sequence[int] = (1, 2, 4, 8),
    page_size: Optional[int] = None,
    persist: bool = True,
) -> List[Dict[str, Any]]:
    """Rank tensor-parallel serving layouts for one model shape with
    the learned cost model — the ``serve.tp_layout`` surface.

    No engines are built: per candidate TP degree the decode step's
    PER-CHIP features are derived analytically from the sharding plan
    (``serve/tp.py``) — the paged attention read's bytes and FLOPs
    scale 1/N (KV pool sharded on heads), dense projections stay
    replicated, and the per-step weight + context gathers add their
    ``(N-1)/N`` bytes — and
    :meth:`~tensorframes_tpu.tune.model.CostModel.predict` turns them
    into a predicted step wall. The model is ridge-fit from the
    observatory's persisted ``programs.jsonl`` FLOP/byte/wall records
    when enough exist — INCLUDING multi-device rows: per-replica
    TP-named programs carry ``meta.tp_degree``, and
    :func:`~tensorframes_tpu.tune.model.per_chip_records` normalizes
    their global estimates to the per-chip unit the candidate features
    use, so mixed-degree serving history fits one coherent model —
    with the analytic roofline prior as the thin-data fallback.

    Returns ``[{"tp": N, "predicted_step_s": ..., "flops": ...,
    "bytes": ...}, ...]`` cheapest-predicted first, and (with
    ``persist`` and tuning not ``off``) pins the winner under
    ``serve.tp_layout`` so benches, ``/statusz``, and operators read
    one store row instead of re-deriving it."""
    import numpy as np

    from ..models.transformer import _kv_heads
    from ..ops.attention import paged_page_size_hint

    params = getattr(model, "params", model)
    n_heads = params["n_heads"]
    d_model = int(np.shape(params["embed"])[1])
    vocab = int(np.shape(params["embed"])[0])
    hd = d_model // n_heads
    n_kv = _kv_heads(params["blocks"][0], d_model, n_heads)
    n_layers = len(params["blocks"])
    blk0 = params["blocks"][0]
    d_ff = int(np.shape(blk0["up"])[1]) if "up" in blk0 else 0
    kv_d = n_kv * hd
    dtype = np.dtype(getattr(params["embed"], "dtype", np.float32))
    itemsize = dtype.itemsize
    ps = page_size or max(
        1, min(int(paged_page_size_hint(dtype, hd)), max_seq_len)
    )
    t = -(-int(max_seq_len) // ps) * ps  # gather span per slot
    s = int(max_slots)
    w_layer = (
        d_model * (d_model + 2 * kv_d)  # qkv
        + d_model * d_model             # proj
        + 2 * d_model * d_ff            # up + down
    ) * itemsize

    def feats(cand: Dict[str, Any]):
        n = int(cand["tp"])
        if n < 1 or n_kv % n or n_heads % n or (d_ff and d_ff % n):
            raise ValueError(f"tp={n} does not divide the model")
        kloc = n_kv // n
        group = n_heads // n_kv
        # paged read per chip: both gathered copies cross HBM, local
        # heads only; scores + weighted sum per local head
        att_bytes = 2.0 * n_layers * s * t * kloc * hd * itemsize
        att_flops = 4.0 * n_layers * s * t * kloc * group * hd
        # dense walk replicated at full shape (weights re-read per step)
        dense_flops = 2.0 * s * (
            n_layers * (
                d_model * (d_model + 2 * kv_d)
                + d_model * d_model
                + 2 * d_model * d_ff
            )
            + d_model * vocab
        )
        dense_bytes = float(
            n_layers * w_layer + vocab * d_model * itemsize
        )
        # the byte-identity plan's collectives: weight shards gathered
        # to full + one per-layer context gather, (n-1)/n received
        frac = (n - 1) / n
        gather_bytes = frac * (
            n_layers * w_layer + n_layers * s * d_model * itemsize
        )
        return (
            att_flops + dense_flops,
            att_bytes + dense_bytes + gather_bytes,
            1.0,
        )

    # the layout winner depends on MODEL SIZE, not just the serving
    # signature (a shallow toy model and a deep production model with
    # the same dtype/head_dim/seq bucket want different degrees) —
    # extend the key with every feature the prediction reads so they
    # never overwrite each other's store row
    sig = (
        serve_signature(dtype, hd, max_seq_len)
        + f"|layers={n_layers}|dff={d_ff}|kv={n_kv}|V={vocab}"
        + f"|slots={s}"
    )
    t_ = tuner()
    cands = [{"tp": int(n)} for n in degrees]
    # fit over the FULL programs.jsonl history including multi-device
    # records: per-replica TP-named step programs carry meta.tp_degree,
    # and per_chip_records folds their global FLOP/byte estimates down
    # to the per-chip unit these candidate features are computed in —
    # multi-device serving rounds sharpen the ranking instead of
    # skewing the fitted rates. Served through the tuner's model cache
    # (one fit per Tuner lifetime; an injected model stays honored).
    ranked = t_.model(per_chip=True).rank(cands, feats)
    out = []
    for cand, pred in ranked:
        f, b, _ = (
            feats(cand) if np.isfinite(pred) else (None, None, None)
        )
        out.append(
            {
                "tp": cand["tp"],
                "predicted_step_s": pred,
                "flops": f,
                "bytes": b,
            }
        )
    if persist and mode() != "off" and out and np.isfinite(
        out[0]["predicted_step_s"]
    ):
        t_.pin("serve.tp_layout", sig, {"tp": out[0]["tp"]})
    return out


def serve_signature(dtype, head_dim: int, max_seq_len: int) -> str:
    """The serving-knob signature: pool dtype kind, head dim, and the
    pow2 sequence bucket — what the page-size/prefill winners key on
    (shared by engine init and :func:`tune_serve_knobs` so they resolve
    the same store rows)."""
    import numpy as np

    kind = np.dtype(dtype).name
    bucket = 1 << max(4, int(max_seq_len - 1).bit_length())
    return f"dtype={kind}|hd={head_dim}|L={bucket}"


def tune_serve_knobs(
    model,
    *,
    max_seq_len: int,
    prompt_len: Optional[int] = None,
    max_new_tokens: int = 16,
    max_slots: int = 4,
    page_sizes: Optional[Sequence[int]] = None,
    prefill_chunks: Optional[Sequence[int]] = None,
    page_slots: Optional[Sequence[Dict[str, int]]] = None,
    draft_params=None,
    draft_lens: Optional[Sequence[int]] = None,
    seed: int = 0,
    repeats: int = 1,
    budget_s: Optional[float] = None,
) -> Dict[str, Dict[str, Any]]:
    """Measure and persist the serving knobs — page size, prefill
    chunk tokens, the pool geometry (``serve.page_slots``: decode
    slots × pages per slot), and (with ``draft_params``) the
    speculative draft length (``serve.draft_len``) — for one model
    shape.

    Engine init consults the store only (building engines inside an
    engine's own constructor is not a sane trial), so the measured
    search for these surfaces lives here: each candidate runs a seeded
    prompt batch through a throwaway
    :class:`~tensorframes_tpu.serve.GenerationEngine`'s prefill +
    decode, and the median-wall winner is persisted for every later
    engine with this signature (``bench.py autotune`` and operators
    call this; byte-identity of the streams across every candidate is
    a serve-suite invariant — page size, chunking, slot count, pool
    size, and draft length never change emitted tokens, only
    scheduling). Throwaway engines are MEMOIZED per engine-level
    config within each surface's grid — candidates that differ only in
    scheduler-side knobs (and repeat trials of one candidate) reuse
    one engine instead of rebuilding per trial, which keeps the
    measured search inside ``tune_budget_s`` on the larger
    speculation-enabled grid and keeps construction wall out of the
    measured steady state; the memo is released between surfaces so at
    most one grid's device pools are ever resident.

    ``page_slots`` candidates are ``{"slots": S, "pages_per_slot": P}``
    dicts (default: the full-coverage geometry plus a half-pool
    oversubscription and a double-slot batch). ``draft_lens``
    candidates (default ``2, 4, 8``) each serve the trial batch
    speculatively; the median verify-inclusive wall — which is exactly
    where the measured acceptance rate and per-dispatch verify cost
    land (the ``serve.spec_acceptance_rate`` gauge and
    ``serve.verify_seconds`` histogram export the series live) —
    decides k. Engines built with the DEFAULT knobs pick winners up
    from the store; explicit arguments always win (docs/tuning.md).

    Returns ``{"serve.page_size": winner, "serve.prefill_chunk":
    winner, "serve.page_slots": winner[, "serve.draft_len": winner]}``.
    """
    import numpy as np

    from ..ops.attention import paged_page_size_hint

    if mode() != "online":
        # lookups below would be read-only: nothing gets measured or
        # persisted, and a defaults-shaped return would masquerade as a
        # tuned result — say so loudly instead of no-op'ing silently
        logger.warning(
            "tune_serve_knobs called with tuning mode %r — the measured "
            "search needs set_config(tune_mode=\"online\") (or "
            "autotune=True / TFT_TUNE unset); returning store/default "
            "resolutions without measuring", mode(),
        )
    if max_new_tokens >= max_seq_len:
        raise ValueError(
            f"max_seq_len ({max_seq_len}) must exceed max_new_tokens "
            f"({max_new_tokens}) — the trial prompts need at least one "
            f"token of room"
        )
    params = getattr(model, "params", model)
    n_heads = params["n_heads"]
    d_model = int(np.shape(params["embed"])[1])
    hd = d_model // n_heads
    dtype = np.dtype(getattr(params["embed"], "dtype", np.float32))
    sig = serve_signature(dtype, hd, max_seq_len)
    plen = prompt_len or max(8, max_seq_len // 2)
    plen = max(1, min(plen, max_seq_len - max_new_tokens))
    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(1, 32, size=plen).astype(np.int32).tolist()
        for _ in range(max_slots)
    ]

    # ONE throwaway engine at a time, keyed by its engine-level config
    # (the satellite fix: a candidate's warmup + repeat trials used to
    # rebuild the engine — pool, weight copy, jit wrappers — per call,
    # blowing the budget on construction wall). A trial whose config
    # matches the resident engine's reuses it; a config change drops
    # the old engine FIRST, so peak device residency stays one pool's
    # footprint — exactly the old per-trial teardown's — instead of a
    # whole grid's pools pinned at once.
    resident: Dict[str, Any] = {"key": None, "eng": None}

    def run_engine(
        page_size: int,
        chunk: int,
        slots: Optional[int] = None,
        pages_per_slot: Optional[int] = None,
        draft_k: int = 0,
    ) -> None:
        from ..serve import GenerationEngine, pages_needed

        slots = int(slots or max_slots)
        num_pages = None
        if pages_per_slot is not None:
            # the feasibility floor: the pool must hold one full-length
            # request even when the candidate oversubscribes
            num_pages = max(
                pages_needed(max_seq_len, int(page_size)),
                slots * int(pages_per_slot),
            )
        key = (int(page_size), int(chunk), slots, num_pages, int(draft_k))
        if resident["key"] != key:
            resident["key"] = resident["eng"] = None  # release first
            kw: Dict[str, Any] = {}
            if draft_k:
                kw = dict(
                    draft_params=draft_params, draft_len=int(draft_k)
                )
            resident["eng"] = GenerationEngine(
                model,
                max_slots=slots,
                page_size=int(page_size),
                num_pages=num_pages,
                max_seq_len=max_seq_len,
                queue_capacity=max(slots, max_slots),
                prefill_chunk_tokens=int(chunk),
                **kw,
            )
            resident["key"] = key
        eng = resident["eng"]
        # drive synchronously (no thread start/stop per trial); the
        # batch drains fully, so the reused engine is idle between
        # trials
        handles = [eng.submit(p, max_new_tokens) for p in prompts]
        eng.run_until_idle()
        for h in handles:
            h.result(timeout=300)

    hint = max(1, min(int(paged_page_size_hint(dtype, hd)), max_seq_len))
    if page_sizes is None:
        page_sizes = sorted({16, max(1, hint // 2), hint})
    if prefill_chunks is None:
        prefill_chunks = sorted({0, max(8, plen // 2)})
    t = tuner()
    ps_winner = t.lookup(
        "serve.page_size", sig, {"page_size": hint},
        grid=[{"page_size": int(p)} for p in page_sizes],
        trial=lambda cand: run_engine(
            cand["page_size"], 0
        ),
        budget_s=budget_s, repeats=repeats,
    )
    pc_winner = t.lookup(
        "serve.prefill_chunk", sig, {"tokens": 0},
        grid=[{"tokens": int(c)} for c in prefill_chunks],
        trial=lambda cand: run_engine(
            int(ps_winner.get("page_size", hint)), cand["tokens"]
        ),
        budget_s=budget_s, repeats=repeats,
    )
    best_ps = int(ps_winner.get("page_size", hint))
    best_pc = int(pc_winner.get("tokens", 0))
    from ..serve import pages_needed as _pages_needed

    full_pps = _pages_needed(max_seq_len, best_ps)
    geo_default = {"slots": int(max_slots), "pages_per_slot": full_pps}
    if page_slots is None:
        page_slots = [
            geo_default,
            # oversubscribe the pool: half the pages, lean on
            # preempt-and-requeue (wins when live tokens << max length)
            {
                "slots": int(max_slots),
                "pages_per_slot": max(1, full_pps // 2),
            },
            # widen the decode batch instead
            {"slots": int(max_slots) * 2, "pages_per_slot": full_pps},
        ]
    geo_winner = t.lookup(
        "serve.page_slots", sig, geo_default,
        grid=[
            {"slots": int(c["slots"]),
             "pages_per_slot": int(c["pages_per_slot"])}
            for c in page_slots
        ],
        trial=lambda cand: run_engine(
            best_ps, best_pc,
            slots=cand["slots"], pages_per_slot=cand["pages_per_slot"],
        ),
        budget_s=budget_s, repeats=repeats,
    )
    out = {
        "serve.page_size": ps_winner,
        "serve.prefill_chunk": pc_winner,
        "serve.page_slots": geo_winner,
    }
    if draft_params is not None:
        # the speculative draft-length search: each candidate k serves
        # the same batch through draft + batched verify; the measured
        # wall folds the acceptance rate and per-dispatch verify cost
        # together, which is the trade k exists to balance
        if draft_lens is None:
            draft_lens = (2, 4, 8)
        cands = sorted(
            {
                max(1, min(int(k), max_seq_len - 1))
                for k in draft_lens
            }
        )
        out["serve.draft_len"] = t.lookup(
            "serve.draft_len", sig, {"k": 4},
            grid=[{"k": k} for k in cands],
            trial=lambda cand: run_engine(
                best_ps, best_pc, draft_k=cand["k"]
            ),
            budget_s=budget_s, repeats=repeats,
        )
    resident["key"] = resident["eng"] = None  # release the last engine
    return out
