"""Persisted tuning database: winners survive restarts, fleet-wide.

One JSONL file — by default ``tune.jsonl`` next to the XLA persistent
compile cache (``~/.cache/tensorframes_tpu``), the same shared home
that lets a fleet of processes reuse each other's compiled programs —
holds every tuned winner, keyed by ``surface | signature | device
kind``. The durability model mirrors the compile cache's:

- **atomic rename writes**: a put re-reads the current file, merges the
  new winner, writes the whole merged state to a uniquely-named temp
  file, fsyncs, and ``os.replace``\\ s it over the target. Concurrent
  writers race at the rename and the last COMPLETE write wins — a
  reader can never observe a torn file, and a writer killed mid-write
  (even ``kill -9``) leaves only a stale temp file behind, never a
  corrupt store;
- **schema versioning**: every record carries ``"v"``; records from a
  different schema version are ignored on read (the consumer simply
  re-tunes), so a binary upgrade never misreads an old store;
- **corrupt-line tolerance**: unparseable lines (a partial write from a
  pre-rename implementation, disk corruption) are skipped with a
  warning, never fatal;
- **cross-process staleness by mtime re-read**: reads go through an
  in-process cache invalidated on ``(mtime_ns, size)`` change, so a
  winner tuned by process A is visible to a long-running process B at
  its next lookup for the cost of one ``stat``.

The store knows nothing about what a config means — it maps key
strings to JSON dicts. :mod:`tensorframes_tpu.tune.search` owns the
semantics (grids, trials, installation).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..utils.logging import get_logger

__all__ = [
    "SCHEMA_VERSION",
    "TuneStore",
    "device_kind",
    "store_path",
]

logger = get_logger("tune.store")

#: bump when the record layout changes incompatibly: old-version records
#: are IGNORED on read (ignore-and-retune), never misinterpreted
SCHEMA_VERSION = 1


_device_kind_cache: Optional[str] = None


def device_kind() -> str:
    """The accelerator kind winners are keyed under — a winner measured
    on one chip generation must not serve another. Cached for the
    process lifetime (the device cannot change under a live runtime,
    and this sits on per-transfer lookup paths)."""
    global _device_kind_cache
    if _device_kind_cache is None:
        try:
            import jax

            _device_kind_cache = str(jax.devices()[0].device_kind)
        except Exception:
            return "unknown"
    return _device_kind_cache


def store_path() -> str:
    """Where the tuning store lives: ``Config.tune_file``, else
    ``$TFT_TUNE_FILE``, else ``tune.jsonl`` next to the XLA compile
    cache directory (same precedence as
    :func:`~tensorframes_tpu.utils.config.enable_compilation_cache` for
    locating that directory)."""
    from ..utils.config import get_config

    explicit = get_config().tune_file or os.environ.get("TFT_TUNE_FILE", "")
    if explicit:
        return explicit
    cache_dir = (
        os.environ.get("TFT_COMPILE_CACHE_DIR")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "tensorframes_tpu",
            "xla-cache",
        )
    )
    return os.path.join(os.path.dirname(cache_dir), "tune.jsonl")


def make_key(surface: str, signature: str, device: Optional[str] = None) -> str:
    return f"{surface}|{signature}|{device if device is not None else device_kind()}"


class TuneStore:
    """The persisted winner map. Thread-safe; see the module docstring
    for the cross-process contract."""

    def __init__(self, path: Optional[str] = None):
        self._explicit_path = path
        self._lock = threading.Lock()
        #: read cache: (resolved path, (mtime_ns, size)) -> entries.
        #: Invalidation is by stat change, so process B sees process A's
        #: winners at its next get() without re-parsing on every lookup.
        self._cache_path: Optional[str] = None
        self._cache_stat: Optional[Tuple[int, int]] = None
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._corrupt_seen = 0

    # -- path / load -------------------------------------------------------

    def path(self) -> str:
        return self._explicit_path or store_path()

    def _stat(self, path: str) -> Optional[Tuple[int, int]]:
        try:
            st = os.stat(path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _parse(
        self, path: str
    ) -> Tuple[Dict[str, Dict[str, Any]], list]:
        """``(entries, foreign_lines)``: current-schema records by key
        (later lines win), plus the RAW lines of valid records from
        OTHER schema versions — invisible to this process
        (ignore-and-retune) but carried verbatim through rewrites so a
        mixed-version fleet sharing one store never erases each other's
        winners."""
        entries: Dict[str, Dict[str, Any]] = {}
        foreign: list = []
        corrupt = 0
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        corrupt += 1
                        continue
                    if not isinstance(rec, dict):
                        corrupt += 1
                        continue
                    if rec.get("v") != SCHEMA_VERSION:
                        # a different schema version is not corruption —
                        # it is simply not for us (ignore-and-retune);
                        # preserved verbatim on rewrite
                        foreign.append(line)
                        continue
                    key = rec.get("key")
                    cfg = rec.get("config")
                    if not isinstance(key, str) or not isinstance(cfg, dict):
                        corrupt += 1
                        continue
                    # later lines win: last-complete-wins per key
                    entries[key] = rec
        except OSError:
            return {}, []
        if corrupt and corrupt != self._corrupt_seen:
            self._corrupt_seen = corrupt
            logger.warning(
                "tuning store %s: %d unparseable line(s) skipped", path,
                corrupt,
            )
        return entries, foreign

    def _load(self) -> Dict[str, Dict[str, Any]]:
        """Entries under the lock-free read path: re-parse only when the
        file's (mtime_ns, size) moved or the resolved path changed."""
        path = self.path()
        st = self._stat(path)
        with self._lock:
            if path == self._cache_path and st == self._cache_stat:
                return self._entries
            self._entries = (
                self._parse(path)[0] if st is not None else {}
            )
            self._cache_path, self._cache_stat = path, st
            return self._entries

    # -- reads -------------------------------------------------------------

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        """The stored record for ``key`` (``None`` when absent). The
        returned dict is the raw record; callers read ``record["config"]``."""
        return self._load().get(key)

    def entries(self) -> Dict[str, Dict[str, Any]]:
        """A snapshot of every stored record, by key."""
        return dict(self._load())

    # -- writes ------------------------------------------------------------

    def put(
        self,
        key: str,
        config: Dict[str, Any],
        *,
        wall_s: Optional[float] = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Record a winner: read-merge-rewrite with an atomic rename.

        The merge re-reads the file immediately before writing so a
        concurrent writer's winners for OTHER keys are carried forward
        whenever the interleaving allows; two simultaneous writers to
        the SAME key race at the rename and the last complete write
        wins. Either way the file always parses."""
        # key = surface | signature | device, where the SIGNATURE may
        # itself contain "|" separators — the device is always the last
        # segment, so split it off from the right
        surface, _, rest = key.partition("|")
        signature, _, device = rest.rpartition("|")
        rec = {
            "v": SCHEMA_VERSION,
            "key": key,
            "surface": surface,
            "signature": signature,
            "device": device,
            "config": dict(config),
            "wall_s": None if wall_s is None else round(float(wall_s), 6),
            "meta": dict(meta or {}),
            "ts": round(time.time(), 3),
            "host": socket.gethostname(),
            "pid": os.getpid(),
        }
        path = self.path()
        with self._lock:
            entries, foreign = self._parse(path)
            entries = dict(entries)
            entries[key] = rec
            self._write(path, entries, foreign)
            self._entries = entries
            self._cache_path = path
            self._cache_stat = self._stat(path)
        return rec

    def clear(self, surface: Optional[str] = None) -> int:
        """Drop every stored winner (or only one surface's); returns the
        number removed. The pin/clear cookbook entry in docs/tuning.md."""
        path = self.path()
        with self._lock:
            entries, foreign = self._parse(path)
            entries = dict(entries)
            if surface is None:
                removed, entries = len(entries), {}
            else:
                victims = [
                    k for k, r in entries.items()
                    if r.get("surface") == surface
                ]
                for k in victims:
                    del entries[k]
                removed = len(victims)
            if removed:
                self._write(path, entries, foreign)
            self._entries = entries
            self._cache_path = path
            self._cache_stat = self._stat(path)
        return removed

    def _write(
        self,
        path: str,
        entries: Dict[str, Dict[str, Any]],
        foreign: list = (),
    ) -> None:
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        # unique temp name per writer: two processes must never share a
        # temp file (the dist-jobs _atomic_write lesson); the rename is
        # the single atomic commit point
        tmp = os.path.join(
            d,
            f".{os.path.basename(path)}.{os.getpid()}."
            f"{threading.get_ident()}.tmp",
        )
        body = "".join(ln + "\n" for ln in foreign) + "".join(
            json.dumps(entries[k], default=str) + "\n"
            for k in sorted(entries)
        )
        try:
            with open(tmp, "w") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass
