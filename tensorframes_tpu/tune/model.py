"""Learned cost predictor: rank the candidate grid so trials stay cheap.

The TPU learned-cost-model line (Kaufman et al., arXiv:2008.01040;
TpuGraphs, arXiv:2308.13490) trains graph networks over kernel features
to predict runtimes. This module is the same idea at this engine's
scale: a **ridge/analytic hybrid** over the three program features the
observatory already persists per compiled program
(``obs/programs.py`` → ``programs.jsonl``: FLOPs, bytes accessed,
per-dispatch wall) —

    wall_per_dispatch  ≈  w_f · flops  +  w_b · bytes  +  w_0

``w_f`` is an effective 1/FLOP-rate, ``w_b`` an effective 1/bandwidth,
``w_0`` the per-dispatch overhead (trace/launch/link latency). The
**analytic prior** seeds those weights from the device's known peaks
(:func:`~tensorframes_tpu.obs.programs.peak_flops` /
``peak_bytes_per_s``, conservative constants on unknown hosts); the
**ridge fit** then re-estimates them from this host's own
``programs.jsonl`` records when enough are available, falling back to
the prior per-weight when the fit goes unphysical (a negative rate).

The autotuner (:mod:`.search`) uses it only to *rank* candidates —
measured trials cover the top-K predicted configs and the measurement
always decides — so a bad prediction costs a wasted trial, never a
wrong winner. Prediction error is exported as the
``tune.predicted_error_ratio`` histogram so the model's honesty is a
dashboard series, not a belief.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import get_logger

__all__ = [
    "CostModel",
    "default_model",
    "load_cost_records",
    "per_chip_records",
]

logger = get_logger("tune.model")

#: conservative fallback rates for hosts with no peak table entry
#: (CPU): a few-GFLOP/s core and a DDR-ish link, plus a dispatch
#: overhead in the tens of microseconds — the ORDERING these produce is
#: what matters, not the absolute walls
_FALLBACK_FLOPS_PER_S = 5e10
_FALLBACK_BYTES_PER_S = 1e10
_DISPATCH_OVERHEAD_S = 5e-5

#: ridge regularizer (features are pre-scaled to O(1), see _fit)
_RIDGE_LAMBDA = 1e-3
#: minimum records before the fit replaces the analytic prior
_MIN_FIT_RECORDS = 8


def _analytic_weights() -> Tuple[float, float, float]:
    from ..obs.programs import peak_bytes_per_s, peak_flops

    pf = peak_flops() or _FALLBACK_FLOPS_PER_S
    pb = peak_bytes_per_s() or _FALLBACK_BYTES_PER_S
    return (1.0 / pf, 1.0 / pb, _DISPATCH_OVERHEAD_S)


class CostModel:
    """``predict(flops, bytes, dispatches)`` → seconds, linear in the
    features with non-negative weights."""

    __slots__ = ("w_flops", "w_bytes", "w_overhead", "source")

    def __init__(
        self,
        w_flops: float,
        w_bytes: float,
        w_overhead: float,
        source: str = "analytic",
    ):
        self.w_flops = float(w_flops)
        self.w_bytes = float(w_bytes)
        self.w_overhead = float(w_overhead)
        self.source = source

    @classmethod
    def analytic(cls) -> "CostModel":
        return cls(*_analytic_weights(), source="analytic")

    @classmethod
    def fit(cls, records: Iterable[Dict[str, Any]]) -> "CostModel":
        """Ridge-fit the weights from program-cost records (rows shaped
        like ``obs/programs.py``'s JSONL: ``flops``, ``bytes``,
        ``dispatches``, ``dispatch_s``). Records without all three
        features, or with zero dispatches, are skipped. Falls back to
        the analytic prior — per weight — when the data is too thin or
        the fit yields a negative rate."""
        prior = _analytic_weights()
        xs: List[Tuple[float, float]] = []
        ys: List[float] = []
        for rec in records:
            flops = rec.get("flops")
            nbytes = rec.get("bytes")
            disp = rec.get("dispatches") or 0
            wall = rec.get("dispatch_s") or 0.0
            if flops is None or nbytes is None or disp <= 0 or wall <= 0:
                continue
            xs.append((float(flops), float(nbytes)))
            ys.append(float(wall) / float(disp))
        if len(xs) < _MIN_FIT_RECORDS:
            return cls(*prior, source="analytic")
        x = np.asarray(xs, dtype=np.float64)
        y = np.asarray(ys, dtype=np.float64)
        # scale features to O(1) so one lambda regularizes both; the
        # intercept column is already O(1)
        scale = np.maximum(x.max(axis=0), 1.0)
        xn = np.concatenate([x / scale, np.ones((len(x), 1))], axis=1)
        a = xn.T @ xn + _RIDGE_LAMBDA * np.eye(3)
        try:
            w = np.linalg.solve(a, xn.T @ y)
        except np.linalg.LinAlgError:
            return cls(*prior, source="analytic")
        w_f, w_b = float(w[0] / scale[0]), float(w[1] / scale[1])
        w_0 = float(w[2])
        # a negative rate is unphysical — that weight keeps its prior
        # (typical when the records do not span that feature's range)
        fitted = (
            w_f if w_f > 0 else prior[0],
            w_b if w_b > 0 else prior[1],
            w_0 if w_0 > 0 else prior[2],
        )
        source = (
            "ridge"
            if (w_f > 0 and w_b > 0 and w_0 > 0)
            else "ridge+analytic"
        )
        return cls(*fitted, source=source)

    def predict(
        self, flops: float, nbytes: float, dispatches: float = 1.0
    ) -> float:
        """Predicted wall seconds for a workload of ``flops`` total
        FLOPs and ``nbytes`` total bytes run as ``dispatches`` program
        dispatches."""
        return (
            self.w_flops * float(flops)
            + self.w_bytes * float(nbytes)
            + self.w_overhead * float(dispatches)
        )

    def rank(
        self,
        candidates: Sequence[Dict[str, Any]],
        feats,
    ) -> List[Tuple[Dict[str, Any], float]]:
        """Candidates with their predicted walls, cheapest-predicted
        first. ``feats(candidate)`` returns ``(flops, bytes,
        dispatches)``; a candidate whose features raise ranks last
        (predicted ``inf``) rather than killing the search."""
        scored: List[Tuple[Dict[str, Any], float]] = []
        for cand in candidates:
            try:
                f, b, d = feats(cand)
                scored.append((cand, self.predict(f, b, d)))
            except Exception:
                scored.append((cand, float("inf")))
        scored.sort(key=lambda cp: cp[1])
        return scored

    def as_dict(self) -> Dict[str, Any]:
        return {
            "w_flops": self.w_flops,
            "w_bytes": self.w_bytes,
            "w_overhead": self.w_overhead,
            "source": self.source,
        }


def load_cost_records(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """The observatory's persisted program-cost rows
    (``programs.jsonl``; corrupt lines skipped) — the training set."""
    from ..obs.programs import costs_path

    target = path or costs_path()
    rows: List[Dict[str, Any]] = []
    try:
        with open(target) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    rows.append(rec)
    except OSError:
        return []
    return rows


def per_chip_records(
    records: Iterable[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Normalize MULTI-DEVICE program records to per-chip features so
    mixed-degree ``programs.jsonl`` histories fit ONE coherent model.

    A tensor-parallel step program's record (``meta.tp_degree = N`` —
    the per-replica TP-named programs ``serve.decode[rX]`` etc. write
    these) carries the WHOLE program's FLOP/byte estimate while its
    measured wall is the per-step wall of N chips working concurrently;
    feeding it into the ridge fit as-is teaches the model an N×-too-slow
    rate. Dividing the features by the degree yields what ONE chip
    computed/moved per dispatch — the same unit
    :func:`~tensorframes_tpu.tune.search.rank_tp_layouts` builds its
    candidate features in, which is what lets the layout ranker learn
    from multi-device serving history instead of single-device-only
    records. Single-device rows pass through unchanged."""
    out: List[Dict[str, Any]] = []
    for rec in records:
        try:
            meta = rec.get("meta") or {}
            tp = int(meta.get("tp_degree") or 1)
        except Exception:
            tp = 1
        if tp > 1:
            rec = dict(rec)
            if rec.get("flops"):
                rec["flops"] = float(rec["flops"]) / tp
            if rec.get("bytes"):
                rec["bytes"] = float(rec["bytes"]) / tp
        out.append(rec)
    return out


def default_model(
    path: Optional[str] = None, per_chip: bool = False
) -> CostModel:
    """The model the tuner uses: ridge-fit from this host's persisted
    program costs when enough records exist, else the analytic prior.
    ``per_chip=True`` normalizes multi-device records first
    (:func:`per_chip_records`) — what the tensor-parallel layout ranker
    wants. Never raises."""
    try:
        records = load_cost_records(path)
        # fold in the LIVE registry too: a fresh process that has
        # already dispatched programs this session has labels that may
        # not have autopersisted yet
        try:
            from ..obs import programs as _programs

            records = records + [r.as_dict() for r in _programs.programs()]
        except Exception:
            pass
        if per_chip:
            records = per_chip_records(records)
        return CostModel.fit(records)
    except Exception:
        logger.warning("cost-model fit failed; using analytic prior",
                       exc_info=True)
        return CostModel.analytic()
